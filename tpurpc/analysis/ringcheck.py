"""Exhaustive SPSC ring protocol model checker.

Models the sequence-stamped ring of :mod:`tpurpc.core.ring` at **word
granularity** (ALIGN = 1 word, header = footer = 1 word, so a message of
``n`` payload words spans ``n + 2``), and exhaustively explores every
writer/reader interleaving on small rings by depth-first search over global
states with memoization. Each shared-memory **word store is one atomic
step** — exactly the granularity at which the real protocol's ordering
argument lives (the release fence before the header store orders it after
the payload+footer stores; under exhaustive interleaving, a wrong order is
a reachable torn state).

What is modeled (mirroring ``ring.py`` / ``ring.cc``):

* message framing ``[header | payload… | footer]`` with the header carrying
  ``(seq, len)`` and the footer carrying the sequence stamp — completion is
  "header seq matches AND footer stamp matches", nothing is ever zeroed;
* the 3-word reserved slack (header + footer + one-word gap) and the credit
  check ``span ≤ capacity − in_flight − 3`` before a write begins;
* credit return: the reader publishes its head as a single shared-word
  store, at a **nondeterministic** moment (any point with unconsumed
  progress), which covers every batching/threshold timing;
* the PR-1 batched ``write_many`` protocol: one bulk placement of all
  payloads+footers (headers withheld), then the per-message header stores
  in order — the single-head-publish batch;
* wrap handling: runs push several messages through capacity-4/8 rings so
  every offset wraps at least once and stale stamps from prior laps are in
  memory during completion checks.

Checked invariants:

* **no torn reads** — every payload word a reader consumes belongs to the
  message (sequence) the framing claimed;
* **no lost or duplicated messages** — at quiescence the reader received
  exactly the sent sequence, in order, payloads intact;
* **publish ordering** — a writer store never lands on a word the reader
  has not yet consumed (one-sided-overwrite ghost check), and the published
  credit head never runs ahead of what was actually consumed.

Seeded mutants (:data:`MUTANTS`) break the protocol in known ways
(publish-before-write, batched headers published before the bulk copy,
ignored credit checks, early reader head publish, misstamped batch footers);
:func:`mutant_kill_suite` asserts the checker rejects every one — the
checker is itself checked.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

#: reserved slack in words: header + footer + one-word gap (ring.py RESERVED)
RESERVED_WORDS = 3

#: memory word tags
_ZERO = ("zero",)


def _span(ln: int) -> int:
    return ln + 2


class Violation(Exception):
    """A protocol invariant failed in some interleaving."""

    def __init__(self, kind: str, detail: str, trace: List[str]):
        super().__init__(f"[{kind}] {detail}")
        self.kind = kind
        self.detail = detail
        self.trace = trace


class CheckResult:
    __slots__ = ("ok", "states", "violation", "config")

    def __init__(self, ok: bool, states: int, violation: Optional[Violation],
                 config: str):
        self.ok = ok
        self.states = states
        self.violation = violation
        self.config = config

    def __repr__(self) -> str:
        if self.ok:
            return f"<ringcheck OK {self.config}: {self.states} states>"
        return (f"<ringcheck VIOLATION {self.config}: {self.violation} "
                f"after {self.states} states>")


#: writer mutants: reorder/weaken the store protocol
#: reader mutants: break the consume/publish ordering
MUTANTS = (
    "publish_before_write",     # header+footer stored BEFORE the payload
    "batch_publish_before_write",  # batch: headers stored before bulk copy
    "ignore_credits",           # writer skips the credit/space check
    "early_head_publish",       # reader advances+publishes before copying
    "batch_misstamped_footer",  # batch: every footer stamped with batch seq0
)


# -- state -------------------------------------------------------------------
#
# Global state is a flat tuple so the DFS memo can hash it:
#   (mem, credit_head,
#    w_tail, w_seq, w_msg_idx, w_pending,           # writer
#    r_head, r_seq, r_phase, r_len, r_idx, r_consumed, received)
#
# w_pending: a tuple of GROUPS. Each group is a tuple of atomic ops that are
# mutually UNORDERED — any op of the first group may fire next (a bulk
# memcpy guarantees nothing about its internal store order, so the model
# must not either); a group only starts once the previous group drained
# (that is what the release fence buys the real protocol). Ops:
#   ("st", abs_off, word) — store `word` at abs offset,
#   ("adv", new_tail, new_seq, n_msgs) — local tail/seq advance.
# r_phase: "scan" | "copy" | ("copy_at", base) for the early-publish mutant


def check_ring(capacity: int, payload_lens: Sequence[int],
               batched: bool = False, mutant: Optional[str] = None,
               max_states: int = 5_000_000) -> CheckResult:
    """Exhaustively check one configuration; returns a :class:`CheckResult`.

    ``payload_lens`` — the payload word counts of the messages to send, in
    order. ``batched=True`` drives the ``write_many`` single-publish
    protocol (as many whole messages per batch as credits allow).
    """
    if mutant is not None and mutant not in MUTANTS:
        raise ValueError(f"unknown mutant {mutant!r}; known: {MUTANTS}")
    cfg = (f"cap={capacity} msgs={list(payload_lens)} "
           f"batched={batched} mutant={mutant}")
    msgs = tuple(payload_lens)
    for ln in msgs:
        if _span(ln) > capacity - 1:
            raise ValueError(f"payload {ln} cannot ever fit capacity "
                             f"{capacity}")

    init = (
        (_ZERO,) * capacity,  # mem
        0,                    # credit_head (shared word)
        0, 0, 0, (),          # w_tail, w_seq, w_msg_idx, w_pending
        0, 0, "scan", 0, 0, 0,  # r_head, r_seq, r_phase, r_len, r_idx, r_consumed
        (),                   # received: tuple of (seq, payload words tuple)
    )

    visited = set()
    # DFS over (state, trace); trace kept short — step labels only
    stack: List[Tuple[tuple, Tuple[str, ...]]] = [(init, ())]
    states = 0
    try:
        while stack:
            state, trace = stack.pop()
            if state in visited:
                continue
            visited.add(state)
            states += 1
            if states > max_states:
                raise RuntimeError(
                    f"state space exceeds {max_states} states ({cfg})")
            succ = _successors(state, msgs, capacity, batched, mutant,
                               trace)
            if not succ:
                _check_quiescent(state, msgs, trace)
                continue
            stack.extend(succ)
    except Violation as v:
        return CheckResult(False, states, v, cfg)
    return CheckResult(True, states, None, cfg)


def _check_quiescent(state, msgs, trace) -> None:
    (mem, credit_head, w_tail, w_seq, w_msg_idx, w_pending,
     r_head, r_seq, r_phase, r_len, r_idx, r_consumed, received) = state
    if w_msg_idx < len(msgs) or w_pending:
        raise Violation(
            "stuck", f"writer wedged at message {w_msg_idx}/{len(msgs)} "
            "with no enabled step (credit starvation or protocol wedge)",
            list(trace))
    if len(received) != len(msgs):
        raise Violation(
            "lost", f"quiescent with {len(received)}/{len(msgs)} messages "
            "delivered", list(trace))
    for i, (seq, words) in enumerate(received):
        if seq != i:
            raise Violation("order", f"message {i} delivered with seq {seq}",
                            list(trace))
        if list(words) != [("pay", i, j) for j in range(msgs[i])]:
            raise Violation("torn", f"message {i} payload corrupt: {words}",
                            list(trace))


def _successors(state, msgs, capacity, batched, mutant, trace):
    (mem, credit_head, w_tail, w_seq, w_msg_idx, w_pending,
     r_head, r_seq, r_phase, r_len, r_idx, r_consumed, received) = state
    succ = []

    # ---- writer steps ----
    if w_pending:
        group = w_pending[0]
        for op in group:
            rest_group = tuple(o for o in group if o is not op)
            rest = ((rest_group,) + w_pending[1:] if rest_group
                    else w_pending[1:])
            if op[0] == "st":
                _, abs_off, word = op
                # ghost overwrite check: a store may never land on a word
                # the reader has not consumed (reader's consumed boundary is
                # r_head; during a copy r_head still sits at the message
                # start).
                if abs_off >= r_head + capacity:
                    raise Violation(
                        "overwrite",
                        f"writer store at abs {abs_off} laps unconsumed "
                        f"reader head {r_head} (capacity {capacity})",
                        list(trace) + [f"w:store@{abs_off}"])
                new_mem = list(mem)
                new_mem[abs_off % capacity] = word
                succ.append((
                    (tuple(new_mem), credit_head,
                     w_tail, w_seq, w_msg_idx, rest,
                     r_head, r_seq, r_phase, r_len, r_idx, r_consumed,
                     received),
                    trace + (f"w:store@{abs_off}",)))
            elif op[0] == "adv":
                _, new_tail, new_seq, n_msgs = op
                succ.append((
                    (mem, credit_head,
                     new_tail, new_seq, w_msg_idx + n_msgs, rest,
                     r_head, r_seq, r_phase, r_len, r_idx, r_consumed,
                     received),
                    trace + ("w:adv",)))
    elif w_msg_idx < len(msgs):
        # begin the next write: fold the credit word, check space, stage the
        # store sequence. One step (the credit word read is one load).
        if credit_head > w_tail:
            raise Violation(
                "credit", f"published credit head {credit_head} ahead of "
                f"writer tail {w_tail}", list(trace) + ["w:begin"])
        pending = _stage_write(msgs, w_msg_idx, w_tail, w_seq, credit_head,
                               capacity, batched, mutant)
        if pending is not None:
            succ.append((
                (mem, credit_head,
                 w_tail, w_seq, w_msg_idx, pending,
                 r_head, r_seq, r_phase, r_len, r_idx, r_consumed, received),
                trace + ("w:begin",)))

    # ---- reader steps ----
    if r_phase == "scan":
        hdr = mem[r_head % capacity]
        if (isinstance(hdr, tuple) and hdr[0] == "hdr" and hdr[1] == r_seq):
            ln = hdr[2]
            ftr = mem[(r_head + 1 + ln) % capacity]
            if ftr == ("ftr", r_seq):
                if mutant == "early_head_publish":
                    # MUTANT: advance + publish the head BEFORE copying
                    succ.append((
                        (mem, r_head + _span(ln),
                         w_tail, w_seq, w_msg_idx, w_pending,
                         r_head + _span(ln), r_seq, ("copy_at", r_head), ln,
                         0, 0, received),
                        trace + ("r:detect!early",)))
                else:
                    succ.append((
                        (mem, credit_head,
                         w_tail, w_seq, w_msg_idx, w_pending,
                         r_head, r_seq, "copy", ln, 0, r_consumed, received),
                        trace + ("r:detect",)))
    elif r_phase == "copy" or (isinstance(r_phase, tuple)
                               and r_phase[0] == "copy_at"):
        base = r_head if r_phase == "copy" else r_phase[1]
        if r_idx < r_len:
            word = mem[(base + 1 + r_idx) % capacity]
            # a mismatched word is a torn read the moment it is consumed
            if word != ("pay", r_seq, r_idx):
                raise Violation(
                    "torn", f"reader consumed {word} for message {r_seq} "
                    f"word {r_idx}", list(trace) + [f"r:copy{r_idx}"])
            succ.append((
                (mem, credit_head,
                 w_tail, w_seq, w_msg_idx, w_pending,
                 r_head, r_seq, r_phase, r_len, r_idx + 1, r_consumed,
                 received),
                trace + (f"r:copy{r_idx}",)))
        else:
            # message complete: advance head (unless the mutant already did)
            new_head = (r_head if isinstance(r_phase, tuple)
                        else r_head + _span(r_len))
            payload = tuple(("pay", r_seq, j) for j in range(r_len))
            succ.append((
                (mem, credit_head,
                 w_tail, w_seq, w_msg_idx, w_pending,
                 new_head, r_seq + 1, "scan", 0, 0,
                 r_consumed + _span(r_len), received + ((r_seq, payload),)),
                trace + ("r:done",)))
    if r_consumed > 0:
        # publish credits: a single shared-word store, at any moment with
        # unpublished progress (covers every threshold/batching timing)
        succ.append((
            (mem, r_head,
             w_tail, w_seq, w_msg_idx, w_pending,
             r_head, r_seq, r_phase, r_len, r_idx, 0, received),
            trace + ("r:publish",)))
    return succ


def _stage_write(msgs, idx, tail, seq, credit_head, capacity, batched,
                 mutant):
    """Stage the atomic store sequence for the next write (or batch).
    Returns None when credits do not admit even one message (step disabled
    until the credit word changes)."""
    in_flight = tail - credit_head
    budget = capacity - in_flight - RESERVED_WORDS
    if mutant == "ignore_credits":
        budget = capacity  # MUTANT: skip the space check entirely
    take: List[int] = []
    for ln in msgs[idx:]:
        if ln > budget:
            break
        take.append(ln)
        budget -= _span(ln)
        if not batched:
            break
    if not take:
        return None

    groups: List[tuple] = []
    if batched and len(take) > 1:
        # write_many: ONE bulk placement (payloads + footers, headers
        # withheld) — a memcpy, so its stores are one UNORDERED group —
        # then the header stores, each its own group, in message order.
        bulk: List[tuple] = []
        headers: List[tuple] = []
        rel = 0
        s = seq
        for ln in take:
            base = tail + rel
            for j in range(ln):
                bulk.append(("st", base + 1 + j, ("pay", s, j)))
            fseq = seq if mutant == "batch_misstamped_footer" else s
            bulk.append(("st", base + 1 + ln, ("ftr", fseq)))
            headers.append(("st", base, ("hdr", s, ln)))
            rel += _span(ln)
            s += 1
        if mutant == "batch_publish_before_write":
            # MUTANT: no ordering between the bulk copy and the header
            # publishes — the batch's completion gates may land first
            groups = [tuple(bulk + headers)]
        else:
            groups = [tuple(bulk)] + [(h,) for h in headers]
        groups.append((("adv", tail + rel, s, len(take)),))
    else:
        ln = take[0]
        payload = tuple(("st", tail + 1 + j, ("pay", seq, j))
                        for j in range(ln))
        footer = ("st", tail + 1 + ln, ("ftr", seq))
        header = ("st", tail, ("hdr", seq, ln))
        if mutant == "publish_before_write":
            # MUTANT: completion gates placed before the payload
            groups = [(header,), (footer,), payload]
        else:
            # the real order: payload (memcpy, unordered), footer, release
            # fence, header
            groups = [payload, (footer,), (header,)]
        groups.append((("adv", tail + _span(ln), seq + 1, 1),))
    return tuple(g for g in groups if g)


# -- MPMC handoff model (tpurpc-manycore, ISSUE 7) ----------------------------
#
# Models tpurpc/core/handoff.py — the bounded MPMC ring carrying sub-batches
# from N per-shard batchers to the single device merger — at the same word
# granularity as the SPSC model: every shared store (a ticket update, one
# payload word, a slot's sequence stamp) is one atomic step, exhaustively
# interleaved. Protocol (Vyukov-style, N producers / 1 consumer):
#
#   producer: t = fetch_add(ticket)          # ONE atomic step (the claim)
#             await seq[t % cap] == t        # slot's previous lap consumed
#             store payload words            # item body, word by word
#             seq[t % cap] = t + 1           # COMMIT, strictly after payload
#   merger:   await seq[h % cap] == h + 1    # commit gate, ticket order
#             read payload words
#             seq[h % cap] = h + cap         # free for lap N+1; h += 1
#
# Invariants: every published item consumed exactly once, untorn (all its
# words name the same (producer, item)), per-producer publish order
# preserved; no wedged quiescent state.

#: seeded MPMC/handoff mutants — each breaks the protocol the way a real
#: sharding bug would, and each must be killed:
#:   handoff_torn_claim         two producers read-then-increment the ticket
#:                              as separate steps → both own one slot (the
#:                              "two producers publishing the same head
#:                              slot" failure)
#:   handoff_commit_before_write  the commit stamp lands before the payload
#:                              → the merger reads a half-written sub-batch
#:   handoff_read_uncommitted   the merger ignores the commit gate and reads
#:                              as soon as a word appears → stale/torn reads
HANDOFF_MUTANTS = (
    "handoff_torn_claim",
    "handoff_commit_before_write",
    "handoff_read_uncommitted",
)

_H_ZERO = ("hzero",)


def check_handoff(n_producers: int = 2, items_per_producer: int = 2,
                  capacity: int = 2, words: int = 2,
                  mutant: Optional[str] = None,
                  max_states: int = 2_000_000) -> CheckResult:
    """Exhaustively interleave N producers against the single merger."""
    if mutant is not None and mutant not in HANDOFF_MUTANTS:
        raise ValueError(f"unknown mutant {mutant!r}; known: "
                         f"{HANDOFF_MUTANTS}")
    cfg = (f"handoff producers={n_producers} items={items_per_producer} "
           f"cap={capacity} words={words} mutant={mutant}")
    total = n_producers * items_per_producer

    # producer state: (phase, t, widx, k)
    #   phase: "idle" | "claimed"(torn-claim midpoint) | "wait" | "write"
    #          | "commit" | "write_after_commit"(commit-first mutant)
    init = (
        0,                                    # ticket
        tuple(range(capacity)),               # seq stamps
        (_H_ZERO,) * (capacity * words),      # payload words
        (("idle", 0, 0, 0),) * n_producers,   # producers
        0, 0, (),                             # h, ridx, current-item words
        (),                                   # received: ((pid, k), ...)
    )
    visited = set()
    stack: List[Tuple[tuple, Tuple[str, ...]]] = [(init, ())]
    states = 0
    try:
        while stack:
            state, trace = stack.pop()
            if state in visited:
                continue
            visited.add(state)
            states += 1
            if states > max_states:
                raise RuntimeError(
                    f"state space exceeds {max_states} states ({cfg})")
            succ = _handoff_successors(state, n_producers,
                                       items_per_producer, capacity, words,
                                       mutant, trace)
            if not succ:
                _handoff_quiescent(state, n_producers, items_per_producer,
                                   total, trace)
                continue
            stack.extend(succ)
    except Violation as v:
        return CheckResult(False, states, v, cfg)
    return CheckResult(True, states, None, cfg)


def _handoff_quiescent(state, n_producers, items_per_producer, total,
                       trace) -> None:
    ticket, seq, data, prods, h, ridx, rwords, received = state
    for pid, (phase, _t, _w, k) in enumerate(prods):
        if phase != "idle" or k < items_per_producer:
            raise Violation(
                "stuck", f"producer {pid} wedged in phase {phase} at item "
                f"{k}/{items_per_producer} with no enabled step",
                list(trace))
    if len(received) != total:
        raise Violation(
            "lost", f"quiescent with {len(received)}/{total} items "
            "delivered", list(trace))
    seen = set(received)
    if len(seen) != len(received):
        raise Violation("dup", f"duplicate delivery: {received}",
                        list(trace))
    for pid in range(n_producers):
        ks = [k for p, k in received if p == pid]
        if ks != sorted(ks) or ks != list(range(items_per_producer)):
            raise Violation(
                "order", f"producer {pid} items delivered as {ks}",
                list(trace))


def _handoff_successors(state, n_producers, items_per_producer, capacity,
                        words, mutant, trace):
    ticket, seq, data, prods, h, ridx, rwords, received = state
    succ = []

    def with_prod(pid, p):
        return prods[:pid] + (p,) + prods[pid + 1:]

    # ---- producer steps ----
    for pid, (phase, t, widx, k) in enumerate(prods):
        slot = None if phase == "idle" else t % capacity
        if phase == "idle" and k < items_per_producer:
            if mutant == "handoff_torn_claim":
                # MUTANT: the claim is read-then-increment, two steps — two
                # producers can read the same ticket and co-own one slot
                succ.append((
                    (ticket, seq, data,
                     with_prod(pid, ("claimed", ticket, 0, k)),
                     h, ridx, rwords, received),
                    trace + (f"p{pid}:claim_read",)))
            else:
                # the real claim: ONE atomic fetch_add (itertools.count)
                succ.append((
                    (ticket + 1, seq, data,
                     with_prod(pid, ("wait", ticket, 0, k)),
                     h, ridx, rwords, received),
                    trace + (f"p{pid}:claim",)))
        elif phase == "claimed":
            # second half of the torn claim: a plain store of t+1 — the
            # lost-update this mutant exists to model (a racing producer's
            # increment is overwritten, and both own ticket t's slot)
            succ.append((
                (t + 1, seq, data,
                 with_prod(pid, ("wait", t, 0, k)),
                 h, ridx, rwords, received),
                trace + (f"p{pid}:claim_inc",)))
        elif phase == "wait":
            if seq[slot] == t:  # slot free for this lap: start writing
                nxt = ("write_after_commit"
                       if mutant == "handoff_commit_before_write"
                       else "write")
                if nxt == "write_after_commit":
                    # MUTANT: commit stamp BEFORE the payload stores
                    new_seq = seq[:slot] + (t + 1,) + seq[slot + 1:]
                    succ.append((
                        (ticket, new_seq, data,
                         with_prod(pid, (nxt, t, 0, k)),
                         h, ridx, rwords, received),
                        trace + (f"p{pid}:commit!early",)))
                else:
                    succ.append((
                        (ticket, seq, data,
                         with_prod(pid, ("write", t, 0, k)),
                         h, ridx, rwords, received),
                        trace + (f"p{pid}:own",)))
        elif phase in ("write", "write_after_commit"):
            if widx < words:
                off = slot * words + widx
                new_data = data[:off] + (("pay", pid, k, widx),) + data[off + 1:]
                succ.append((
                    (ticket, seq, new_data,
                     with_prod(pid, (phase, t, widx + 1, k)),
                     h, ridx, rwords, received),
                    trace + (f"p{pid}:w{widx}",)))
            elif phase == "write_after_commit":
                # commit already landed (mutant): item done
                succ.append((
                    (ticket, seq, data,
                     with_prod(pid, ("idle", 0, 0, k + 1)),
                     h, ridx, rwords, received),
                    trace + (f"p{pid}:done",)))
            else:
                new_seq = seq[:slot] + (t + 1,) + seq[slot + 1:]
                succ.append((
                    (ticket, new_seq, data,
                     with_prod(pid, ("idle", 0, 0, k + 1)),
                     h, ridx, rwords, received),
                    trace + (f"p{pid}:commit",)))

    # ---- merger steps (single consumer, ticket order) ----
    slot = h % capacity
    if mutant == "handoff_read_uncommitted":
        readable = data[slot * words][0] == "pay"  # MUTANT: no commit gate
    else:
        readable = seq[slot] == h + 1
    if readable and len(received) < n_producers * items_per_producer:
        if ridx < words:
            word = data[slot * words + ridx]
            succ.append((
                (ticket, seq, data, prods,
                 h, ridx + 1, rwords + (word,), received),
                trace + (f"m:r{ridx}",)))
        else:
            # item complete: torn unless every word names ONE (pid, k)
            heads = {(w[1], w[2]) for w in rwords if w[0] == "pay"}
            if len(heads) != 1 or any(w[0] != "pay" for w in rwords) \
                    or [w[3] for w in rwords] != list(range(words)):
                raise Violation(
                    "torn", f"merger consumed mixed/stale words {rwords} "
                    f"at slot {slot}", list(trace) + ["m:done"])
            item = next(iter(heads))
            new_seq = seq[:slot] + (h + capacity,) + seq[slot + 1:]
            succ.append((
                (ticket, new_seq, data, prods,
                 h + 1, 0, (), received + (item,)),
                trace + ("m:done",)))
    return succ


def handoff_default_suite(verbose: bool = False) -> List[CheckResult]:
    """Clean handoff configs the CLI exhausts alongside the SPSC suite."""
    configs = [
        dict(n_producers=2, items_per_producer=2, capacity=2, words=2),
        dict(n_producers=2, items_per_producer=1, capacity=2, words=3),
        dict(n_producers=3, items_per_producer=1, capacity=2, words=2),
    ]
    out = []
    for cfg in configs:
        res = check_handoff(**cfg)
        out.append(res)
        if verbose:
            print(f"  {res!r}")
    return out


def handoff_mutant_kill_suite(verbose: bool = False) -> Dict[str, bool]:
    """Every seeded handoff mutant must produce a violation somewhere."""
    kill_configs = {
        "handoff_torn_claim": [
            dict(n_producers=2, items_per_producer=2, capacity=2, words=2)],
        "handoff_commit_before_write": [
            dict(n_producers=2, items_per_producer=1, capacity=2, words=2)],
        "handoff_read_uncommitted": [
            dict(n_producers=2, items_per_producer=2, capacity=2, words=2)],
    }
    out = {}
    for mutant, configs in kill_configs.items():
        killed = False
        for cfg in configs:
            res = check_handoff(mutant=mutant, **cfg)
            if not res.ok:
                killed = True
                if verbose:
                    print(f"  mutant {mutant}: KILLED — {res.violation}")
                break
        if not killed and verbose:
            print(f"  mutant {mutant}: SURVIVED")
        out[mutant] = killed
    return out


# -- rendezvous bulk-transfer model (tpurpc-express, ISSUE 9) -----------------
#
# Models tpurpc/core/rendezvous.py — the offer/claim/write/complete protocol
# moving bulk payloads by one-sided writes into a receiver-advertised landing
# region — at the same word granularity: every region word store, every
# control-message consumption, every consumer action is one atomic step,
# exhaustively interleaved. Control messages ride ordered queues (the framed
# connection preserves order); the region and its doorbell word are shared
# memory.
#
#   sender:   OFFER(k) → await CLAIM(lease) → store payload words →
#             COMPLETE(k, lease)  [standing mode: subsequent messages skip
#             OFFER/CLAIM and gate on the region's doorbell word instead]
#   receiver: OFFER → grant the region (when free) → CLAIM;
#             COMPLETE → read the region words (the zero-copy delivery),
#             hold the alias until the nondeterministic consumer-free step
#             (weakref-finalize in the implementation), which re-checks the
#             words and rings the doorbell
#   death:    with_death=True explores sender death at every point; the
#             receiver's close must release the claimed region
#
# Invariants: every message delivered exactly once in order with intact
# payload; a delivered-and-still-aliased region is never overwritten (the
# reuse-only-after-complete-and-free rule); a dead peer's claimed region is
# released; no wedged quiescent states.

RDV_MUTANTS = (
    "write_before_claim",    # sender stores payload before the claim/
    #                          doorbell says the region is its to write
    "complete_before_write",  # COMPLETE control message sent before the
    #                          payload stores (delivery reads torn words)
)

_R_ZERO = ("rzero",)


def check_rendezvous(messages: int = 2, words: int = 2,
                     standing: bool = True, with_death: bool = False,
                     mutant: Optional[str] = None,
                     max_states: int = 2_000_000) -> CheckResult:
    """Exhaustively interleave one sender, the receiver's control loop, and
    the consumer over a single landing region."""
    if mutant is not None and mutant not in RDV_MUTANTS:
        raise ValueError(f"unknown mutant {mutant!r}; known: {RDV_MUTANTS}")
    cfg = (f"rendezvous msgs={messages} words={words} standing={standing} "
           f"death={with_death} mutant={mutant}")

    # state:
    #  (sr, rs,                  control queues (ordered, like the framing)
    #   mem, doorbell,           region words + consumer-freed count
    #   s_phase, s_k, s_w, s_used, s_grant, s_alive,
    #   r_lease, r_phase, r_k, r_w, delivered, alias, closed)
    # s_phase: idle|wait|write|dead-ish via s_alive; r_lease: 0 = not
    # granted, else the granted lease id; r_phase: "ctrl" | "deliver";
    # alias: None or (k,) the consumer still holds
    init = ((), (), (_R_ZERO,) * words, 0,
            "idle", 0, 0, 0, 0, True,
            0, "ctrl", 0, 0, (), None, False)
    visited = set()
    stack: List[Tuple[tuple, Tuple[str, ...]]] = [(init, ())]
    states = 0
    try:
        while stack:
            state, trace = stack.pop()
            if state in visited:
                continue
            visited.add(state)
            states += 1
            if states > max_states:
                raise RuntimeError(
                    f"state space exceeds {max_states} states ({cfg})")
            succ = _rdv_successors(state, messages, words, standing,
                                   with_death, mutant, trace)
            if not succ:
                _rdv_quiescent(state, messages, trace)
                continue
            stack.extend(succ)
    except Violation as v:
        return CheckResult(False, states, v, cfg)
    return CheckResult(True, states, None, cfg)


def _rdv_quiescent(state, messages, trace) -> None:
    (sr, rs, mem, doorbell, s_phase, s_k, s_w, s_used, s_grant, s_alive,
     r_lease, r_phase, r_k, r_w, delivered, alias, closed) = state
    if alias is not None:
        raise Violation("stuck", "quiescent with a live consumer alias",
                        list(trace))
    if s_alive:
        if s_k < messages:
            raise Violation(
                "stuck", f"sender wedged at message {s_k}/{messages}",
                list(trace))
        if delivered != tuple(range(messages)):
            raise Violation(
                "lost", f"quiescent with deliveries {delivered} "
                f"(wanted 0..{messages - 1} in order)", list(trace))
    else:
        # peer death: the receiver's close must have run and released the
        # claimed region — a leaked claim pins pool memory forever
        if r_lease:
            raise Violation(
                "leak", "sender died but the claimed landing region was "
                "never released", list(trace))
        if list(delivered) != sorted(set(delivered)) or any(
                delivered[i] != i for i in range(len(delivered))):
            raise Violation(
                "order", f"out-of-order deliveries {delivered} before the "
                "death", list(trace))


def _rdv_successors(state, messages, words, standing, with_death, mutant,
                    trace):
    (sr, rs, mem, doorbell, s_phase, s_k, s_w, s_used, s_grant, s_alive,
     r_lease, r_phase, r_k, r_w, delivered, alias, closed) = state
    succ = []

    def mk(sr=sr, rs=rs, mem=mem, doorbell=doorbell, s_phase=s_phase,
           s_k=s_k, s_w=s_w, s_used=s_used, s_grant=s_grant,
           s_alive=s_alive, r_lease=r_lease, r_phase=r_phase, r_k=r_k,
           r_w=r_w, delivered=delivered, alias=alias, closed=closed,
           step=""):
        return ((sr, rs, mem, doorbell, s_phase, s_k, s_w, s_used,
                 s_grant, s_alive, r_lease, r_phase, r_k, r_w, delivered,
                 alias, closed), trace + (step,))

    # ---- sender ----
    if s_alive and s_k < messages:
        if s_phase == "idle":
            if s_grant:
                if mutant == "write_before_claim" or doorbell == s_used:
                    # correct: gate on the doorbell (the consumer freed
                    # every previous use); MUTANT: skip the gate
                    succ.append(mk(s_phase="write", s_w=0,
                                   step="s:own" if doorbell == s_used
                                   else "s:own!early"))
            else:
                nxt = ("write" if mutant == "write_before_claim"
                       else "wait")
                succ.append(mk(sr=sr + (("offer", s_k),), s_phase=nxt,
                               s_w=0, step="s:offer"))
        elif s_phase == "wait":
            if rs and rs[0][0] == "claim":
                succ.append(mk(rs=rs[1:], s_grant=rs[0][1],
                               s_phase="write", s_w=0, step="s:claim"))
        elif s_phase == "write":
            if mutant == "complete_before_write" and s_w == 0 \
                    and s_phase != "completed":
                # MUTANT: the COMPLETE control message leaves first
                succ.append(mk(sr=sr + (("complete", s_k, s_grant),),
                               s_phase="write2", step="s:complete!early"))
            elif s_w < words:
                nm = list(mem)
                nm[s_w] = ("pay", s_k, s_w)
                succ.append(mk(mem=tuple(nm), s_w=s_w + 1,
                               step=f"s:w{s_w}"))
            else:
                succ.append(mk(sr=sr + (("complete", s_k, s_grant),),
                               s_phase="idle", s_k=s_k + 1,
                               s_used=s_used + 1,
                               s_grant=s_grant if standing else 0,
                               step="s:complete"))
        elif s_phase == "write2":  # mutant: stores after the early complete
            if s_w < words:
                nm = list(mem)
                nm[s_w] = ("pay", s_k, s_w)
                succ.append(mk(mem=tuple(nm), s_w=s_w + 1,
                               step=f"s:w{s_w}"))
            else:
                succ.append(mk(s_phase="idle", s_k=s_k + 1,
                               s_used=s_used + 1,
                               s_grant=s_grant if standing else 0,
                               step="s:done"))
    if with_death and s_alive:
        succ.append(mk(s_alive=False, step="s:die"))

    # ---- receiver control loop ----
    if r_phase == "ctrl" and sr:
        kind = sr[0][0]
        if kind == "offer":
            # grant only a FREE region (granted/aliased = pool empty; the
            # offer defers — the implementation would refuse-and-fallback,
            # which is outside this model's scope), and never after close
            # (a closed link refuses every op — granting after the peer's
            # death released everything would leak the region forever)
            if not r_lease and alias is None and not closed:
                lease = len(delivered) + s_used + 1  # unique enough
                succ.append(mk(sr=sr[1:], r_lease=lease,
                               rs=rs + (("claim", lease),),
                               step="r:claim"))
        else:  # complete
            _, k, lease = sr[0]
            if lease and lease == r_lease:
                succ.append(mk(sr=sr[1:], r_phase="deliver", r_k=k, r_w=0,
                               step="r:begin"))
            else:
                # unknown/never-claimed lease: the implementation drops the
                # completion (the message is LOST — quiescence catches it)
                succ.append(mk(sr=sr[1:], step="r:drop"))
    elif r_phase == "deliver":
        if r_w < words:
            word = mem[r_w]
            if word != ("pay", r_k, r_w):
                raise Violation(
                    "torn", f"delivery of message {r_k} read {word} at "
                    f"word {r_w}", list(trace) + [f"r:r{r_w}"])
            succ.append(mk(r_w=r_w + 1, step=f"r:r{r_w}"))
        else:
            succ.append(mk(r_phase="ctrl", delivered=delivered + (r_k,),
                           alias=(r_k,),
                           r_lease=r_lease if standing else 0,
                           step="r:deliver"))

    # ---- consumer: holds the alias, then frees (weakref-finalize) ----
    if alias is not None:
        for j in range(words):
            if mem[j] != ("pay", alias[0], j):
                raise Violation(
                    "overwrite", f"region overwritten while message "
                    f"{alias[0]}'s delivery is still aliased: word {j} = "
                    f"{mem[j]}", list(trace) + ["c:free"])
        succ.append(mk(alias=None, doorbell=doorbell + 1, step="c:free"))

    # ---- receiver close after peer death ----
    if not s_alive and not closed:
        succ.append(mk(r_lease=0, closed=True, step="r:close"))

    return succ


# -- kv block-table handoff model (tpurpc-keystone, ISSUE 11) ----------------
#
# One sequence's KV blocks move from a SOURCE (prefill server or migrating
# decode server) into a DEST decode arena:
#
#   source: OFFER → await CLAIM(blocks) → one-sided write each block
#           (writes LAND asynchronously — the RDMA-straggler danger) →
#           COMPLETE → await ACK, then free the local copy
#   dest:   OFFER → grant B free blocks → CLAIM; COMPLETE (processable
#           only once every issued write has landed — frame-after-payload
#           ordering) → verify + ADOPT → ACK; a pending (claimed,
#           un-completed) handoff may be REAPED at any time (TTL expiry /
#           source death): its blocks are QUARANTINED, never re-leased —
#           a late landing write must hit dead memory; a COMPLETE for a
#           reaped handoff is NAK'd (the source fails that sequence
#           ALONE).
#
# Invariants: an adopted sequence's blocks hold exactly the payload words
# (torn otherwise); a landing write must never hit a block re-leased to a
# NEW owner (stale-write); the source never wedges (every path reaches
# done/failed); a dead source's claimed blocks end quarantined.

KV_MUTANTS = (
    "kv_reuse_before_quarantine",  # dest returns reaped blocks to the
    #                                free list — a straggling one-sided
    #                                write then lands in re-leased memory
    "kv_free_before_complete",     # source frees its local copy while
    #                                block writes are still outstanding —
    #                                the remaining writes ship junk
)


def check_kv_handoff(blocks: int = 2, with_death: bool = False,
                     mutant: Optional[str] = None,
                     max_states: int = 2_000_000) -> CheckResult:
    """Exhaustively interleave the source, the async write landings, and
    the dest's control loop over one block-table handoff."""
    if mutant is not None and mutant not in KV_MUTANTS:
        raise ValueError(f"unknown mutant {mutant!r}; known: {KV_MUTANTS}")
    cfg = f"kv_handoff blocks={blocks} death={with_death} mutant={mutant}"
    B = blocks
    # state: (sq, dq, s_phase, s_w, s_blocks, s_alive, s_freed, failed,
    #         pending, free, claimed, mem, quarantined, new_owner,
    #         adopted, reaped)
    init = ((), (), "idle", 0, (), True, False, False,
            (), tuple(range(B)), (), ("z",) * B, (), (), False, False)
    visited = set()
    stack: List[Tuple[tuple, Tuple[str, ...]]] = [(init, ())]
    states = 0
    try:
        while stack:
            state, trace = stack.pop()
            if state in visited:
                continue
            visited.add(state)
            states += 1
            if states > max_states:
                raise RuntimeError(
                    f"state space exceeds {max_states} states ({cfg})")
            succ = _kv_successors(state, B, with_death, mutant, trace)
            if not succ:
                _kv_quiescent(state, trace)
                continue
            stack.extend(succ)
    except Violation as v:
        return CheckResult(False, states, v, cfg)
    return CheckResult(True, states, None, cfg)


def _kv_quiescent(state, trace) -> None:
    (sq, dq, s_phase, s_w, s_blocks, s_alive, s_freed, failed,
     pending, free, claimed, mem, quarantined, new_owner,
     adopted, reaped) = state
    if pending:
        raise Violation("stuck", "quiescent with unlanded writes",
                        list(trace))
    if s_alive and s_phase != "done":
        raise Violation("stuck", f"source wedged in phase {s_phase}",
                        list(trace))
    if s_alive and not failed and not adopted:
        raise Violation("lost", "source finished cleanly but the dest "
                        "never adopted the sequence", list(trace))
    if claimed:
        raise Violation("leak", "quiescent with a claimed, unresolved "
                        "handoff (neither adopted nor reaped)",
                        list(trace))
    if adopted and failed:
        raise Violation("split", "sequence both adopted at the dest and "
                        "failed at the source", list(trace))


def _kv_successors(state, B, with_death, mutant, trace):
    (sq, dq, s_phase, s_w, s_blocks, s_alive, s_freed, failed,
     pending, free, claimed, mem, quarantined, new_owner,
     adopted, reaped) = state
    succ = []

    def mk(sq=sq, dq=dq, s_phase=s_phase, s_w=s_w, s_blocks=s_blocks,
           s_alive=s_alive, s_freed=s_freed, failed=failed,
           pending=pending, free=free, claimed=claimed, mem=mem,
           quarantined=quarantined, new_owner=new_owner, adopted=adopted,
           reaped=reaped, step=""):
        return ((sq, dq, s_phase, s_w, s_blocks, s_alive, s_freed,
                 failed, pending, free, claimed, mem, quarantined,
                 new_owner, adopted, reaped), trace + (step,))

    # ---- source ----
    if s_alive and not failed:
        if s_phase == "idle":
            succ.append(mk(sq=sq + (("offer",),), s_phase="wait",
                           step="s:offer"))
        elif s_phase == "wait" and dq and dq[0][0] == "claim":
            succ.append(mk(dq=dq[1:], s_blocks=dq[0][1], s_phase="write",
                           s_w=0, step="s:claim"))
        elif s_phase == "write":
            if s_w < B:
                val = "junk" if s_freed else ("pay", s_w)
                freed = s_freed or (mutant == "kv_free_before_complete"
                                    and s_w == 0)
                succ.append(mk(pending=pending
                               + ((s_blocks[s_w], val),),
                               s_w=s_w + 1, s_freed=freed,
                               step=f"s:w{s_w}"))
            else:
                succ.append(mk(sq=sq + (("complete",),),
                               s_phase="finish", step="s:complete"))
        elif s_phase == "finish" and dq:
            if dq[0][0] == "ack":
                succ.append(mk(dq=dq[1:], s_phase="done", s_freed=True,
                               step="s:ack"))
            elif dq[0][0] == "nak":
                # the dest reaped this handoff: the sequence fails ALONE
                succ.append(mk(dq=dq[1:], s_phase="done", s_freed=True,
                               failed=True, step="s:nak"))
    if with_death and s_alive:
        succ.append(mk(s_alive=False, step="s:die"))

    # ---- async write landings (any order — the RDMA straggler) ----
    for i in range(len(pending)):
        blk, val = pending[i]
        if blk in new_owner:
            raise Violation(
                "stale-write", f"a landing one-sided write hit block "
                f"{blk}, which was re-leased to a new owner — reaped "
                "blocks must QUARANTINE, never re-enter the free list",
                list(trace) + [f"wire:land{blk}"])
        nm = list(mem)
        nm[blk] = val
        succ.append(mk(pending=pending[:i] + pending[i + 1:],
                       mem=tuple(nm), step=f"wire:land{blk}"))

    # ---- dest control loop ----
    if sq:
        kind = sq[0][0]
        if kind == "offer":
            if not claimed and not reaped and len(free) >= B:
                grant = tuple(sorted(free)[:B])
                rest = tuple(b for b in free if b not in grant)
                succ.append(mk(sq=sq[1:], free=rest, claimed=grant,
                               dq=dq + (("claim", grant),),
                               step="d:claim"))
        else:  # complete
            if claimed:
                # frame-after-payload: the COMPLETE is processable only
                # once every issued write has landed
                if not pending:
                    for i, blk in enumerate(claimed):
                        if mem[blk] != ("pay", i):
                            raise Violation(
                                "torn", f"adopt read {mem[blk]} at block "
                                f"{blk} (wanted ('pay', {i})) — the "
                                "source freed/corrupted its copy before "
                                "the handoff completed",
                                list(trace) + ["d:adopt"])
                    succ.append(mk(sq=sq[1:], claimed=(), adopted=True,
                                   dq=dq + (("ack",),), step="d:adopt"))
            else:
                # reaped (or never-claimed) handoff: NAK — the source
                # fails that sequence alone, blocks stay quarantined
                succ.append(mk(sq=sq[1:], dq=dq + (("nak",),),
                               step="d:nak"))
    # reap: TTL expiry / death detection on a pending handoff
    if claimed and not adopted:
        if mutant == "kv_reuse_before_quarantine":
            succ.append(mk(free=tuple(sorted(free + claimed)),
                           claimed=(), reaped=True, step="d:reap!free"))
        else:
            succ.append(mk(quarantined=tuple(sorted(quarantined
                                                    + claimed)),
                           claimed=(), reaped=True, step="d:reap"))
    # a later local sequence leases a free block (bounded to one)
    if reaped and free and not new_owner:
        b = free[0]
        nm = list(mem)
        nm[b] = "new"
        succ.append(mk(free=free[1:], new_owner=(b,), mem=tuple(nm),
                       step=f"d:lease{b}"))

    return succ


def kv_default_suite(verbose: bool = False) -> List[CheckResult]:
    """Clean kv-handoff configs: 2- and 3-block tables, with and without
    source-death-at-every-point."""
    configs = [
        dict(blocks=2),
        dict(blocks=3),
        dict(blocks=2, with_death=True),
        dict(blocks=3, with_death=True),
    ]
    out = []
    for cfg in configs:
        res = check_kv_handoff(**cfg)
        out.append(res)
        if verbose:
            print(f"  {res!r}")
    return out


def kv_mutant_kill_suite(verbose: bool = False) -> Dict[str, bool]:
    """Every seeded kv-handoff mutant must produce a violation."""
    out = {}
    for mutant in KV_MUTANTS:
        killed = False
        for cfg in (dict(blocks=2), dict(blocks=2, with_death=True)):
            res = check_kv_handoff(mutant=mutant, **cfg)
            if not res.ok:
                killed = True
                if verbose:
                    print(f"  mutant {mutant}: KILLED — {res.violation}")
                break
        if not killed and verbose:
            print(f"  mutant {mutant}: SURVIVED")
        out[mutant] = killed
    return out


# -- ctrl descriptor-ring model (tpurpc-pulse, ISSUE 13) ----------------------
#
# Models tpurpc/core/ctrlring.py — the shared-memory descriptor ring carrying
# rendezvous control ops (OFFER/CLAIM/COMPLETE/RELEASE) between two processes
# — at word granularity: every slot word store, the per-batch cons_head
# publish, the parked-flag handshake and the framed kick are each one atomic
# step, exhaustively interleaved.
#
#   producer (per op): read cons_head; FULL (seq - cons_head >= nslots) =>
#             step disabled (the implementation falls back to the framed
#             path; either way it must never overwrite) | store payload
#             words (unordered group) | store the seq stamp STRICTLY after |
#             read parked; if set, enqueue one framed kick
#   consumer: poll the head slot's stamp; == head+1 => read payload words
#             (torn check per word), consume; publish cons_head at a
#             NONDETERMINISTIC moment (covers every batching) | park: set
#             parked, then MANDATORY re-check once, then block until a kick
#   death:    with_death=True explores producer death at every point; the
#             consumer may then close — delivered must be an in-order prefix
#
# Invariants: every op delivered exactly once in order, untorn; no store on
# an unconsumed slot; no wedged quiescent state (a lost wakeup IS a wedge).

CTRL_MUTANTS = (
    "ctrl_publish_before_write",   # stamp stored before/with the payload —
    #                                the consumer reads a torn record
    "ctrl_reuse_before_doorbell",  # producer skips the cons_head full
    #                                check — laps the unconsumed reader
    "ctrl_park_no_redrain",        # consumer parks without the mandatory
    #                                re-check — the post/park race loses
    #                                the wakeup and the link wedges
)

_C_ZERO = ("czero",)


def check_ctrlring(nslots: int = 2, ops: int = 3, words: int = 2,
                   with_death: bool = False, mutant: Optional[str] = None,
                   max_states: int = 2_000_000) -> CheckResult:
    """Exhaustively interleave the producer, the consumer and the framed
    kick queue over one descriptor ring."""
    if mutant is not None and mutant not in CTRL_MUTANTS:
        raise ValueError(f"unknown mutant {mutant!r}; known: {CTRL_MUTANTS}")
    cfg = (f"ctrlring nslots={nslots} ops={ops} words={words} "
           f"death={with_death} mutant={mutant}")
    # state:
    #  (mem,          nslots*(words+1) words: [stamp, payload...] per slot
    #   cons_pub,     published cons_head (shared word)
    #   parked,       consumer-parked flag (shared word)
    #   kicks,        framed kick queue depth (ordered, lossless)
    #   p_seq, p_pending, p_alive,
    #   c_head, c_phase, c_idx, c_unpub, received, closed)
    # c_phase: "poll" | "park_chk" | "parked" | ("copy", idx done via c_idx)
    init = ((_C_ZERO,) * (nslots * (words + 1)), 0, 0, 0,
            0, (), True,
            0, "poll", 0, 0, (), False)
    visited = set()
    stack: List[Tuple[tuple, Tuple[str, ...]]] = [(init, ())]
    states = 0
    try:
        while stack:
            state, trace = stack.pop()
            if state in visited:
                continue
            visited.add(state)
            states += 1
            if states > max_states:
                raise RuntimeError(
                    f"state space exceeds {max_states} states ({cfg})")
            succ = _ctrl_successors(state, nslots, ops, words, with_death,
                                    mutant, trace)
            if not succ:
                _ctrl_quiescent(state, ops, trace)
                continue
            stack.extend(succ)
    except Violation as v:
        return CheckResult(False, states, v, cfg)
    return CheckResult(True, states, None, cfg)


def _ctrl_quiescent(state, ops, trace) -> None:
    (mem, cons_pub, parked, kicks, p_seq, p_pending, p_alive,
     c_head, c_phase, c_idx, c_unpub, received, closed) = state
    if p_alive:
        if p_seq < ops or p_pending:
            raise Violation(
                "stuck", f"producer wedged at op {p_seq}/{ops}",
                list(trace))
        if len(received) != ops:
            # the park-without-redrain mutant's signature: a posted record
            # ages in the ring while the consumer sleeps with no kick
            raise Violation(
                "stuck", f"quiescent with {len(received)}/{ops} ops "
                "delivered and the consumer parked — a lost wakeup",
                list(trace))
        if list(received) != list(range(ops)):
            raise Violation("order", f"ops delivered as {received}",
                            list(trace))
    else:
        got = list(received)
        if got != list(range(len(got))):
            raise Violation(
                "order", f"out-of-order deliveries {received} before the "
                "producer's death", list(trace))


def _ctrl_successors(state, nslots, ops, words, with_death, mutant, trace):
    (mem, cons_pub, parked, kicks, p_seq, p_pending, p_alive,
     c_head, c_phase, c_idx, c_unpub, received, closed) = state
    succ = []

    def mk(mem=mem, cons_pub=cons_pub, parked=parked, kicks=kicks,
           p_seq=p_seq, p_pending=p_pending, p_alive=p_alive,
           c_head=c_head, c_phase=c_phase, c_idx=c_idx, c_unpub=c_unpub,
           received=received, closed=closed, step=""):
        return ((mem, cons_pub, parked, kicks, p_seq, p_pending, p_alive,
                 c_head, c_phase, c_idx, c_unpub, received, closed),
                trace + (step,))

    def slot_base(seq):
        return (seq % nslots) * (words + 1)

    # ---- producer ----
    if p_alive and p_pending:
        group = p_pending[0]
        for op in group:
            rest_group = tuple(o for o in group if o is not op)
            rest = ((rest_group,) + p_pending[1:] if rest_group
                    else p_pending[1:])
            if op[0] == "st":
                _, seq, widx, word = op
                # overwrite check: the slot's previous-lap record must be
                # CONSUMED before any store lands on it
                prev = seq - nslots
                if prev >= 0 and c_head <= prev:
                    raise Violation(
                        "overwrite",
                        f"producer store for op {seq} laps the unconsumed "
                        f"consumer (head {c_head}, slot lap {prev})",
                        list(trace) + [f"p:st{seq}.{widx}"])
                nm = list(mem)
                nm[slot_base(seq) + widx] = word
                succ.append(mk(mem=tuple(nm), p_pending=rest,
                               step=f"p:st{seq}.{widx}"))
            elif op[0] == "chk_parked":
                # read parked AFTER the stamp store: kick when set
                if parked:
                    succ.append(mk(p_pending=rest, kicks=kicks + 1,
                                   step="p:kick"))
                else:
                    succ.append(mk(p_pending=rest, step="p:chk"))
    elif p_alive and p_seq < ops:
        # begin the next op: fold the published cons_head, check space
        full = p_seq - cons_pub >= nslots
        if mutant == "ctrl_reuse_before_doorbell":
            full = False  # MUTANT: no full check at all
        if not full:
            payload = tuple(("st", p_seq, 1 + j, ("pay", p_seq, j))
                            for j in range(words))
            stamp = ("st", p_seq, 0, ("stamp", p_seq + 1))
            if mutant == "ctrl_publish_before_write":
                # MUTANT: stamp and payload land in one unordered group
                groups = (payload + (stamp,), (("chk_parked",),))
            else:
                groups = (payload, (stamp,), (("chk_parked",),))
            succ.append(mk(p_seq=p_seq + 1, p_pending=groups,
                           step=f"p:begin{p_seq}"))
    if with_death and p_alive:
        succ.append(mk(p_alive=False, step="p:die"))

    # ---- consumer ----
    if not closed:
        base = slot_base(c_head)
        ready = mem[base] == ("stamp", c_head + 1)
        if c_phase == "poll":
            if c_idx == 0 and not ready:
                # nothing readable: the consumer MAY decide to park (it
                # may also just keep polling — both schedules explored).
                # The DECISION and the flag store are separate steps: the
                # producer can stamp-and-check-parked in the gap, which is
                # exactly the race the mandatory re-drain closes.
                succ.append(mk(c_phase="park_intent", step="c:park_decide"))
            if ready or c_idx > 0:
                if c_idx < words:
                    word = mem[base + 1 + c_idx]
                    if word != ("pay", c_head, c_idx):
                        raise Violation(
                            "torn", f"consumer read {word} for op "
                            f"{c_head} word {c_idx}",
                            list(trace) + [f"c:r{c_idx}"])
                    succ.append(mk(c_idx=c_idx + 1, step=f"c:r{c_idx}"))
                else:
                    succ.append(mk(c_head=c_head + 1, c_idx=0,
                                   c_unpub=c_unpub + 1,
                                   received=received + (c_head,),
                                   step="c:done"))
            if kicks:  # absorb a stale kick (a frame read, no-op)
                succ.append(mk(kicks=kicks - 1, step="c:kick_absorb"))
        elif c_phase == "park_intent":
            succ.append(mk(parked=1, c_phase="park_chk",
                           step="c:park_flag"))
        elif c_phase == "park_chk":
            # the MANDATORY re-check between flag store and blocking —
            # the lost-wakeup close the ctrl_park_no_redrain mutant skips
            if mutant == "ctrl_park_no_redrain":
                succ.append(mk(c_phase="parked", step="c:parked!blind"))
            elif ready:
                succ.append(mk(parked=0, c_phase="poll",
                               step="c:unpark_found"))
            else:
                succ.append(mk(c_phase="parked", step="c:parked"))
        elif c_phase == "parked":
            if kicks:
                succ.append(mk(kicks=kicks - 1, parked=0, c_phase="poll",
                               step="c:woken"))
        if c_unpub:
            # publish cons_head: one shared-word store, any moment with
            # unpublished progress (covers every batch size)
            succ.append(mk(cons_pub=c_head, c_unpub=0, step="c:publish"))
    # close after producer death (the link teardown wakes the reader)
    if not p_alive and not closed:
        succ.append(mk(closed=True, parked=0, step="c:close"))

    return succ


def ctrl_default_suite(verbose: bool = False) -> List[CheckResult]:
    """Clean ctrl-ring configs: wrap (ops > nslots), both with and without
    producer-death-at-every-point."""
    configs = [
        dict(nslots=2, ops=3, words=2),
        dict(nslots=2, ops=4, words=1),
        dict(nslots=3, ops=4, words=2),
        dict(nslots=2, ops=3, words=2, with_death=True),
    ]
    out = []
    for cfg in configs:
        res = check_ctrlring(**cfg)
        out.append(res)
        if verbose:
            print(f"  {res!r}")
    return out


def ctrl_mutant_kill_suite(verbose: bool = False) -> Dict[str, bool]:
    """Every seeded ctrl-ring mutant must produce a violation."""
    out = {}
    for mutant in CTRL_MUTANTS:
        killed = False
        for cfg in (dict(nslots=2, ops=3, words=2),
                    dict(nslots=2, ops=4, words=1)):
            res = check_ctrlring(mutant=mutant, **cfg)
            if not res.ok:
                killed = True
                if verbose:
                    print(f"  mutant {mutant}: KILLED — {res.violation}")
                break
        if not killed and verbose:
            print(f"  mutant {mutant}: SURVIVED")
        out[mutant] = killed
    return out


# -- suites ------------------------------------------------------------------

def default_suite(verbose: bool = False) -> List[CheckResult]:
    """The bounded exhaustive pass the CLI runs: capacity ≤ 4-word rings
    fully exhausted for the single-message protocol (with wrap), plus the
    batched ``write_many`` protocol and a mixed-size run at capacity 8,
    plus the MPMC handoff (shard → merger) configurations."""
    configs = [
        dict(capacity=4, payload_lens=[1, 1, 1], batched=False),
        dict(capacity=4, payload_lens=[1, 1, 1, 1], batched=False),
        dict(capacity=8, payload_lens=[1, 2, 1], batched=False),
        dict(capacity=8, payload_lens=[1, 1, 1], batched=True),
        dict(capacity=8, payload_lens=[2, 1, 2], batched=True),
    ]
    out = []
    for cfg in configs:
        res = check_ring(**cfg)
        out.append(res)
        if verbose:
            print(f"  {res!r}")
    out.extend(handoff_default_suite(verbose=verbose))
    out.extend(rendezvous_default_suite(verbose=verbose))
    out.extend(kv_default_suite(verbose=verbose))
    out.extend(ctrl_default_suite(verbose=verbose))
    return out


def rendezvous_default_suite(verbose: bool = False) -> List[CheckResult]:
    """Clean rendezvous configs (tpurpc-express, ISSUE 9): solicited and
    standing modes, multi-message reuse, and sender-death-at-every-point
    runs proving a claimed region always releases."""
    configs = [
        dict(messages=2, words=2, standing=True),
        dict(messages=2, words=2, standing=False),
        dict(messages=3, words=2, standing=True),
        dict(messages=2, words=3, standing=False),
        dict(messages=2, words=2, standing=True, with_death=True),
        dict(messages=2, words=2, standing=False, with_death=True),
    ]
    out = []
    for cfg in configs:
        res = check_rendezvous(**cfg)
        out.append(res)
        if verbose:
            print(f"  {res!r}")
    return out


def rendezvous_mutant_kill_suite(verbose: bool = False) -> Dict[str, bool]:
    """Every seeded rendezvous mutant must produce a violation in at least
    one mode."""
    out = {}
    for mutant in RDV_MUTANTS:
        killed = False
        for standing in (True, False):
            res = check_rendezvous(messages=2, words=2, standing=standing,
                                   mutant=mutant)
            if not res.ok:
                killed = True
                if verbose:
                    print(f"  mutant {mutant}: KILLED — {res.violation}")
                break
        if not killed and verbose:
            print(f"  mutant {mutant}: SURVIVED")
        out[mutant] = killed
    return out


def mutant_kill_suite(verbose: bool = False) -> Dict[str, bool]:
    """Run every seeded mutant; a mutant is *killed* when at least one
    configuration produces a violation. Returns {mutant: killed}."""
    kill_configs = {
        "publish_before_write": [
            dict(capacity=8, payload_lens=[1, 1, 1], batched=False)],
        "batch_publish_before_write": [
            dict(capacity=8, payload_lens=[1, 1], batched=True)],
        "ignore_credits": [
            dict(capacity=4, payload_lens=[1, 1, 1], batched=False)],
        "early_head_publish": [
            dict(capacity=4, payload_lens=[1, 1, 1], batched=False)],
        "batch_misstamped_footer": [
            dict(capacity=8, payload_lens=[1, 1], batched=True)],
    }
    out = {}
    for mutant, configs in kill_configs.items():
        killed = False
        for cfg in configs:
            res = check_ring(mutant=mutant, **cfg)
            if not res.ok:
                killed = True
                if verbose:
                    print(f"  mutant {mutant}: KILLED — {res.violation}")
                break
        if not killed and verbose:
            print(f"  mutant {mutant}: SURVIVED")
        out[mutant] = killed
    out.update(handoff_mutant_kill_suite(verbose=verbose))
    out.update(rendezvous_mutant_kill_suite(verbose=verbose))
    out.update(kv_mutant_kill_suite(verbose=verbose))
    out.update(ctrl_mutant_kill_suite(verbose=verbose))
    return out
