"""tpurpc-proof: deterministic schedule exploration over the LIVE code.

The analysis gate's model checkers (``ringcheck``) prove hand-written
*models* of the ring/handoff/rendezvous/KV protocols exhaustively — but a
model proof says nothing about the threaded Python that claims to
implement it. This module is the other half of the "runtime matches
model" guarantee: a CHESS-style deterministic concurrency explorer
(Musuvathi & Qadeer, PLDI'07 — iterative context bounding) that runs the
REAL classes under a cooperative scheduler and exhaustively explores
bounded-preemption interleavings of small harness scenarios.

How the real code becomes schedulable
-------------------------------------

* **The factory seam.** Scenario objects are constructed while an
  exploration is active, so every ``make_lock``/``make_condition`` call
  (the same seam ``TPURPC_DEBUG_LOCKS`` rides) hands back a
  :class:`SchedLock`/:class:`SchedCondition` — lock acquire/release,
  condition wait/notify become scheduling points, and a blocked task is
  *parked in the scheduler*, not in the OS.
* **Line-granular sched points.** Each explored task thread runs under a
  ``sys.settrace`` hook filtered to the scenario's instrumented module
  files: every LINE of the real class is a potential preemption point.
  Two GIL-atomic stores on consecutive lines (a payload store and its
  publish stamp) get a scheduling point between them — exactly the
  granularity the ``publish-before-store`` mutant class needs.
* **Shimmed waits.** ``threading.Event`` uses the harness-injected
  :class:`SchedEvent`; timed waits never sleep — a timed waiter parks,
  and its timeout "fires" (deterministically, oldest first) only when no
  task is runnable, which is exactly the semantics the real code must
  tolerate (a timeout is always legal; the shim just makes it prompt).

Exploration
-----------

One *schedule* is the sequence of task picks made at every scheduling
point. The explorer runs depth-first over the tree of picks with
**iterative preemption bounding**: switching away from a still-runnable
task costs one preemption, switching on a block/finish is free, and only
schedules with at most ``preemption_bound`` preemptions are explored —
the CHESS result that almost every concurrency bug hides within 2
preemptions, which keeps tiny scenarios exhaustive in seconds. The
default continuation policy (run the current task until it blocks) makes
the whole search deterministic: same scenario, same bound → same
schedules in the same order, and any violating schedule's trace (a list
of task ids) replays to the same violation via :func:`replay`.

A violation is a deadlock (all tasks parked on untimed waits), a task
exception, a scenario invariant failure after all tasks finish, or a
diverged schedule (step bound exceeded — clean scenarios never spin).

Scenarios over the live classes live at the bottom of this module
(:data:`SCENARIOS`); the seeded real-code mutants the explorer must kill
(a removed lock, a hoisted publish, a skipped quarantine) live in
:mod:`tpurpc.analysis.schedmutants`, whose file is instrumented too so
the mutated lines get the same scheduling points.

CLI: ``python -m tpurpc.analysis schedule [--quick]`` — the quick suite
(clean scenarios + the mutant kill check at bound 1) rides the default
gate and the ``tools/check.sh`` ``schedule-quick`` stage.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from tpurpc.analysis import locks as _locks

__all__ = [
    "Scenario", "ExploreResult", "Violation", "SchedViolation",
    "SchedLock", "SchedRLock", "SchedCondition", "SchedEvent",
    "explore", "explore_random", "replay", "run_scenario",
    "SCENARIOS", "SCHED_MUTANTS", "quick_suite", "mutant_kill_suite",
]


class SchedViolation(AssertionError):
    """Raised by a scenario's ``check`` when an invariant does not hold."""


class _Abort(BaseException):
    """Internal: unwind a task thread after the run is over (never leaks
    out of the wrapper)."""


# ---------------------------------------------------------------------------
# Tasks and the cooperative scheduler.
# ---------------------------------------------------------------------------

class _Task:
    __slots__ = ("tid", "fn", "sem", "state", "block_kind", "block_obj",
                 "timed", "park_seq", "woke_by_timeout", "exc", "thread",
                 "name")

    def __init__(self, tid: int, fn: Callable, name: str):
        self.tid = tid
        self.fn = fn
        self.name = name
        self.sem = threading.Semaphore(0)
        self.state = "new"          # new | runnable | blocked | finished
        self.block_kind = None      # "lock" | "cond" | "event"
        self.block_obj = None
        self.timed = False
        self.park_seq = 0
        self.woke_by_timeout = False
        self.exc: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


class _BranchPoint:
    __slots__ = ("index", "candidates", "chosen", "preemptions_before",
                 "prev", "prev_runnable")

    def __init__(self, index, candidates, chosen, preemptions_before,
                 prev, prev_runnable):
        self.index = index
        self.candidates = candidates
        self.chosen = chosen
        self.preemptions_before = preemptions_before
        self.prev = prev
        self.prev_runnable = prev_runnable


class Violation:
    """One found bug: ``kind`` is ``deadlock`` / ``exception`` /
    ``invariant`` / ``divergence``; ``trace`` (a list of task ids — the
    full pick sequence) replays it deterministically."""

    __slots__ = ("kind", "message", "trace")

    def __init__(self, kind: str, message: str, trace: List[int]):
        self.kind = kind
        self.message = message
        self.trace = list(trace)

    def __repr__(self) -> str:
        return (f"Violation({self.kind}: {self.message!r}, "
                f"trace={len(self.trace)} picks)")


class ExploreResult:
    __slots__ = ("scenario", "ok", "schedules", "violation", "steps",
                 "capped", "preemption_bound")

    def __init__(self, scenario: str, ok: bool, schedules: int,
                 violation: Optional[Violation], steps: int, capped: bool,
                 preemption_bound: int):
        self.scenario = scenario
        self.ok = ok
        self.schedules = schedules
        self.violation = violation
        self.steps = steps
        self.capped = capped
        self.preemption_bound = preemption_bound

    def __repr__(self) -> str:
        s = "OK" if self.ok else f"VIOLATION {self.violation!r}"
        return (f"<schedule {self.scenario}: {s}, "
                f"{self.schedules} schedules, {self.steps} steps, "
                f"bound {self.preemption_bound}"
                + (", CAPPED" if self.capped else "") + ">")


#: one exploration at a time: the factory hook is process-global
_explore_mu = threading.Lock()


class _Scheduler:
    """One scenario execution under one schedule prefix. The control
    thread (the caller) runs this; task threads hand control back and
    forth through per-task semaphores so exactly one thread — task or
    control — ever runs at a time."""

    def __init__(self, instrument_files: Set[str], max_steps: int):
        self._files = instrument_files
        self.max_steps = max_steps
        self.tasks: List[_Task] = []
        self.aborting = False
        self.diverged = False
        self._ctl_sem = threading.Semaphore(0)
        self._park_counter = itertools.count(1)
        self._tls = threading.local()
        self._hook_threads: Set[int] = {threading.get_ident()}
        self.steps = 0
        self.trace: List[int] = []
        self.branch_points: List[_BranchPoint] = []
        self.preemptions = 0

    # -- task-side plumbing ---------------------------------------------------

    def current(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    def owns_current_thread(self) -> bool:
        return threading.get_ident() in self._hook_threads

    def sched_point(self) -> None:
        """A visible operation on the current task thread: hand control to
        the scheduler and wait to be picked again."""
        task = self.current()
        if task is None:
            return
        if self.aborting:
            raise _Abort()
        self.steps += 1
        if self.steps > self.max_steps:
            self.diverged = True
            self.aborting = True
            self._ctl_sem.release()
            raise _Abort()
        task.state = "runnable"
        self._ctl_sem.release()
        task.sem.acquire()
        if self.aborting:
            raise _Abort()

    def block(self, task: _Task, kind: str, obj, timed: bool) -> None:
        """Park the current task on ``obj`` until a waker (or, for timed
        waits, the scheduler's deterministic timeout) re-enables it."""
        if self.aborting:
            raise _Abort()
        task.state = "blocked"
        task.block_kind = kind
        task.block_obj = obj
        task.timed = timed
        task.park_seq = next(self._park_counter)
        self._ctl_sem.release()
        task.sem.acquire()
        if self.aborting:
            raise _Abort()

    def unblock(self, task: _Task) -> None:
        """Mark a parked task runnable (called by the waker — another task
        thread or the control thread; never schedules it directly)."""
        if task.state == "blocked":
            task.state = "runnable"
            task.block_kind = None
            task.block_obj = None
            task.timed = False

    def wake_waiters_of(self, obj, kind: str) -> None:
        for t in self.tasks:
            if t.state == "blocked" and t.block_kind == kind \
                    and t.block_obj is obj:
                self.unblock(t)

    # -- line tracing ---------------------------------------------------------

    def _make_trace(self, task: _Task):
        files = self._files
        sched_point = self.sched_point

        def local_trace(frame, event, arg):
            if event == "line":
                sched_point()
            return local_trace

        def global_trace(frame, event, arg):
            if event == "call" and frame.f_code.co_filename in files:
                return local_trace
            return None

        return global_trace

    def _wrapper(self, task: _Task, state, started: threading.Semaphore):
        self._tls.task = task
        self._hook_threads.add(threading.get_ident())
        started.release()
        task.sem.acquire()  # first grant
        if self.aborting:
            task.state = "finished"
            self._ctl_sem.release()
            return
        sys.settrace(self._make_trace(task))
        try:
            task.fn(state)
        except _Abort:
            pass
        except BaseException as exc:  # a task exception IS a finding
            task.exc = exc
        finally:
            sys.settrace(None)
            task.state = "finished"
            # extra permits during an abort are harmless (control is in
            # _abort_all, not parked on the semaphore)
            self._ctl_sem.release()

    # -- the run --------------------------------------------------------------

    def run(self, scenario: "Scenario", prefix: Sequence[int],
            preemption_bound: int) -> Optional[Violation]:
        hook_self = self

        def factory_hook(kind, name, lock):
            if not hook_self.owns_current_thread():
                return None
            if kind == "lock":
                return SchedLock(hook_self, name)
            if kind == "rlock":
                return SchedRLock(hook_self, name)
            if kind == "condition":
                return SchedCondition(hook_self, name, lock)
            if kind == "event":
                return SchedEvent(hook_self, name)
            return None

        _locks.set_factory_hook(factory_hook)
        try:
            state = scenario.setup(self)
        except BaseException:
            _locks.set_factory_hook(None)
            raise
        started = threading.Semaphore(0)
        try:
            for i, fn in enumerate(scenario.threads):
                task = _Task(i, fn, f"t{i}")
                self.tasks.append(task)
            for task in self.tasks:
                task.thread = threading.Thread(
                    target=self._wrapper, args=(task, state, started),
                    daemon=True, name=f"tpurpc-sched-{task.tid}")
                task.thread.start()
            for _ in self.tasks:
                started.acquire()
            for task in self.tasks:
                task.state = "runnable"

            violation = self._schedule_loop(prefix, preemption_bound)
            if violation is None:
                for task in self.tasks:
                    if task.exc is not None:
                        violation = Violation(
                            "exception",
                            f"task {task.tid} raised "
                            f"{type(task.exc).__name__}: {task.exc}",
                            self.trace)
                        break
            if violation is None:
                try:
                    scenario.check(state)
                except AssertionError as exc:
                    violation = Violation("invariant", str(exc), self.trace)
            return violation
        finally:
            self._abort_all()
            _locks.set_factory_hook(None)
            try:
                scenario.teardown(state)
            except Exception:
                pass

    def _schedule_loop(self, prefix: Sequence[int],
                       preemption_bound: int) -> Optional[Violation]:
        prev: Optional[int] = None
        while True:
            runnable = [t for t in self.tasks if t.state == "runnable"]
            if not runnable:
                blocked = [t for t in self.tasks if t.state == "blocked"]
                if not blocked:
                    return None  # all finished
                timed = [t for t in blocked if t.timed]
                if not timed:
                    detail = ", ".join(
                        f"t{t.tid} on {t.block_kind} "
                        f"{getattr(t.block_obj, 'name', '?')}"
                        for t in blocked)
                    return Violation(
                        "deadlock",
                        f"all live tasks parked on untimed waits ({detail})",
                        self.trace)
                # deterministic timeout: the longest-parked timed waiter
                t = min(timed, key=lambda t: t.park_seq)
                t.woke_by_timeout = True
                self.unblock(t)
                continue
            candidates = tuple(sorted(t.tid for t in runnable))
            idx = len(self.trace)
            prev_runnable = prev is not None and prev in candidates
            if idx < len(prefix):
                chosen = prefix[idx]
                if chosen not in candidates:
                    # the prefix no longer matches (can only happen on a
                    # hand-edited trace): fall back to the default policy
                    chosen = prev if prev_runnable else candidates[0]
            elif prev_runnable:
                chosen = prev
            else:
                chosen = candidates[0]
            if len(candidates) > 1:
                self.branch_points.append(_BranchPoint(
                    idx, candidates, chosen, self.preemptions, prev,
                    prev_runnable))
            if prev_runnable and chosen != prev:
                self.preemptions += 1
            self.trace.append(chosen)
            task = self.tasks[chosen]
            prev = chosen
            task.sem.release()
            self._ctl_sem.acquire()
            if self.diverged:
                return Violation(
                    "divergence",
                    f"schedule exceeded {self.max_steps} scheduling points "
                    "(a spin the shimmed waits cannot park?)", self.trace)

    def _abort_all(self) -> None:
        self.aborting = True
        for task in self.tasks:
            # generous releases: a task may be parked in block() or
            # sched_point(); extra permits are harmless (thread exits)
            task.sem.release()
            task.sem.release()
        for task in self.tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Scheduler-aware primitives (what the factory seam hands out).
# ---------------------------------------------------------------------------

class SchedLock:
    """A mutex whose contention is resolved by the exploration scheduler.
    Mutual exclusion itself still rests on a real ``threading.Lock`` (so a
    stray non-task thread can never corrupt it); task threads park in the
    scheduler instead of the OS."""

    _reentrant = False

    def __init__(self, sched: _Scheduler, name: str):
        self._sched = sched
        self.name = name
        self._inner = threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._sched
        task = sched.current()
        if task is None:
            return self._inner.acquire(blocking, timeout)
        if self._reentrant and self._owner == task.tid:
            self._count += 1
            return True
        sched.sched_point()
        while True:
            if self._inner.acquire(blocking=False):
                self._owner = task.tid
                self._count = 1
                return True
            if not blocking:
                return False
            sched.block(task, "lock", self,
                        timed=(timeout is not None and timeout >= 0))
            if task.woke_by_timeout:
                task.woke_by_timeout = False
                return False

    def release(self) -> None:
        sched = self._sched
        task = sched.current()
        if task is None:
            self._inner.release()
            return
        if self._reentrant and self._count > 1:
            self._count -= 1
            return
        self._release_nopoint()
        sched.sched_point()

    def _release_nopoint(self) -> None:
        """Release and wake lock-waiters WITHOUT a scheduling point — the
        condition-wait path, where release+park must be one atomic step
        from the model's point of view."""
        self._owner = None
        self._count = 0
        self._inner.release()
        self._sched.wake_waiters_of(self, "lock")

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SchedLock {self.name}>"


class SchedRLock(SchedLock):
    _reentrant = True


class SchedCondition:
    """Condition variable over a :class:`SchedLock`, scheduler-parked.
    ``wait`` registers the waiter, releases the lock and parks as ONE
    model step (no lost wakeups the real primitive would not have);
    ``notify`` wakes the longest-parked waiter(s), which then re-contend
    for the lock like real threads do."""

    def __init__(self, sched: _Scheduler, name: str, lock=None):
        self._sched = sched
        self.name = name
        self._lock = lock if lock is not None else SchedLock(sched, name)
        self._waiters: List[_Task] = []

    # delegate the lock face
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        return self._lock.__enter__()

    def __exit__(self, *exc) -> None:
        self._lock.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        task = sched.current()
        if task is None:  # non-task thread: degrade to a poll loop
            self._lock.release()
            time.sleep(min(timeout or 0.01, 0.01))
            self._lock.acquire()
            return True
        self._waiters.append(task)
        self._lock._release_nopoint()
        sched.block(task, "cond", self, timed=timeout is not None)
        timed_out = task.woke_by_timeout
        task.woke_by_timeout = False
        if task in self._waiters:
            self._waiters.remove(task)
        self._lock.acquire()
        return not timed_out

    def wait_for(self, predicate, timeout: Optional[float] = None) -> bool:
        result = predicate()
        while not result:
            if not self.wait(timeout):
                return predicate()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        sched = self._sched
        for _ in range(n):
            if not self._waiters:
                break
            t = self._waiters.pop(0)
            sched.unblock(t)
        if sched.current() is not None:
            sched.sched_point()

    def notify_all(self) -> None:
        self.notify(len(self._waiters) or 0)

    def __repr__(self) -> str:
        return f"<SchedCondition {self.name}>"


class SchedEvent:
    """Harness-injected stand-in for ``threading.Event`` on scenario
    objects (``ring._data_evt = sched_event``): waits park in the
    scheduler, timeouts fire only when nothing else can run."""

    def __init__(self, sched: _Scheduler, name: str = "event"):
        self._sched = sched
        self.name = name
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._sched.wake_waiters_of(self, "event")
        if self._sched.current() is not None:
            self._sched.sched_point()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        task = sched.current()
        if task is None:
            deadline = time.monotonic() + (timeout or 0.01)
            while not self._flag and time.monotonic() < deadline:
                time.sleep(0.001)
            return self._flag
        sched.sched_point()
        if self._flag:
            return True
        sched.block(task, "event", self, timed=timeout is not None)
        task.woke_by_timeout = False
        return self._flag


# ---------------------------------------------------------------------------
# Scenarios.
# ---------------------------------------------------------------------------

class Scenario:
    """One explorable harness over live classes.

    ``setup(sched)`` builds the scenario state (factory-made locks become
    Sched primitives while it runs); ``threads`` are the racing bodies
    (each called with the state); ``check(state)`` asserts the invariant
    after every thread finished; ``instrument`` lists the module FILES
    whose lines are scheduling points; ``teardown(state)`` releases any
    real resources (arenas, pools) after each run."""

    def __init__(self, name: str, setup, threads, check,
                 instrument: Sequence[str], teardown=None,
                 max_steps: int = 60000):
        self.name = name
        self.setup = setup
        self.threads = list(threads)
        self.check = check
        self.instrument = set(instrument)
        self.teardown = teardown or (lambda state: None)
        self.max_steps = max_steps


def _module_file(mod) -> str:
    return mod.__file__


def _run_once(scenario: Scenario, prefix: Sequence[int],
              preemption_bound: int) -> Tuple[Optional[Violation],
                                              _Scheduler]:
    sched = _Scheduler(scenario.instrument, scenario.max_steps)
    violation = sched.run(scenario, prefix, preemption_bound)
    return violation, sched


def explore(scenario: Scenario, preemption_bound: int = 2,
            max_schedules: int = 20000) -> ExploreResult:
    """Depth-first exploration of all schedules within the preemption
    bound (or until ``max_schedules``). Deterministic: same scenario +
    bound → same schedules in the same order."""
    with _explore_mu:
        return _explore_locked(scenario, preemption_bound, max_schedules)


def _explore_locked(scenario: Scenario, preemption_bound: int,
                    max_schedules: int) -> ExploreResult:
    stack: List[Tuple[int, ...]] = [()]
    schedules = 0
    steps = 0
    while stack:
        if schedules >= max_schedules:
            return ExploreResult(scenario.name, True, schedules, None,
                                 steps, True, preemption_bound)
        prefix = stack.pop()
        violation, sched = _run_once(scenario, prefix, preemption_bound)
        schedules += 1
        steps += sched.steps
        if violation is not None:
            return ExploreResult(scenario.name, False, schedules,
                                 violation, steps, False, preemption_bound)
        # push unexplored alternatives discovered at or after the prefix
        for bp in reversed(sched.branch_points):
            if bp.index < len(prefix):
                continue
            for alt in bp.candidates:
                if alt == bp.chosen:
                    continue
                cost = 1 if (bp.prev_runnable and alt != bp.prev) else 0
                if bp.preemptions_before + cost > preemption_bound:
                    continue
                stack.append(tuple(sched.trace[:bp.index]) + (alt,))
    return ExploreResult(scenario.name, True, schedules, None, steps,
                         False, preemption_bound)


class _ScriptScheduler(_Scheduler):
    def __init__(self, files, max_steps, script):
        super().__init__(files, max_steps)
        self._script = script

    def _schedule_loop(self, prefix, preemption_bound):
        # identical to the base loop, except picks come from the script
        prev: Optional[int] = None
        while True:
            runnable = [t for t in self.tasks if t.state == "runnable"]
            if not runnable:
                blocked = [t for t in self.tasks if t.state == "blocked"]
                if not blocked:
                    return None
                timed = [t for t in blocked if t.timed]
                if not timed:
                    detail = ", ".join(
                        f"t{t.tid} on {t.block_kind}" for t in blocked)
                    return Violation("deadlock",
                                     f"all live tasks parked ({detail})",
                                     self.trace)
                t = min(timed, key=lambda t: t.park_seq)
                t.woke_by_timeout = True
                self.unblock(t)
                continue
            candidates = tuple(sorted(t.tid for t in runnable))
            idx = len(self.trace)
            if idx < len(self._script):
                chosen = candidates[self._script[idx] % len(candidates)]
            else:
                chosen = (prev if prev is not None and prev in candidates
                          else candidates[0])
            if prev is not None and prev in candidates and chosen != prev:
                self.preemptions += 1
            self.trace.append(chosen)
            task = self.tasks[chosen]
            prev = chosen
            task.sem.release()
            self._ctl_sem.acquire()
            if self.diverged:
                return Violation("divergence",
                                 f"exceeded {self.max_steps} points",
                                 self.trace)


def explore_random(scenario: Scenario, seed: int,
                   schedules: int = 50) -> Tuple[ExploreResult,
                                                 List[List[int]]]:
    """Seeded random-walk exploration: each schedule's picks come from a
    seeded PRNG script (reduced modulo the live candidate set at every
    point). Same seed → identical schedule traces — the determinism
    contract tests/test_schedule.py pins. Returns ``(result, traces)``."""
    import random

    rng = random.Random(seed)
    traces: List[List[int]] = []
    steps = 0
    with _explore_mu:
        for i in range(schedules):
            script = [rng.randrange(1 << 16) for _ in range(8192)]
            sched = _ScriptScheduler(scenario.instrument,
                                     scenario.max_steps, script)
            violation = sched.run(scenario, (), 1 << 30)
            traces.append(list(sched.trace))
            steps += sched.steps
            if violation is not None:
                return (ExploreResult(scenario.name, False, i + 1,
                                      violation, steps, False, -1), traces)
    return (ExploreResult(scenario.name, True, schedules, None, steps,
                          False, -1), traces)


def replay(scenario: Scenario, trace: Sequence[int]) -> ExploreResult:
    """Re-run one serialized schedule (a violating trace from a previous
    exploration). Deterministic: the same trace drives the same picks, so
    a violation replays to the same violation."""
    with _explore_mu:
        violation, sched = _run_once(scenario, tuple(trace), 1 << 30)
        return ExploreResult(scenario.name, violation is None, 1,
                             violation, sched.steps, False, -1)


# ---------------------------------------------------------------------------
# The live-code scenarios.
# ---------------------------------------------------------------------------

def _handoff_scenario() -> Scenario:
    """Two producers race ``HandoffRing.publish`` against one consumer
    draining in ticket order — the PR 7 merge-boundary protocol, run for
    real. Invariant: both items arrive, exactly once, no Nones."""
    from tpurpc.core import handoff as _handoff

    def setup(sched: _Scheduler):
        ring = _handoff.HandoffRing(capacity=4)
        ring._data_evt = SchedEvent(sched, "handoff._data_evt")
        ring._space_evt = SchedEvent(sched, "handoff._space_evt")
        return {"ring": ring, "got": []}

    def producer(tag):
        def body(state):
            ok = state["ring"].publish(tag, timeout=None)
            assert ok, f"publish({tag!r}) returned False"
        return body

    def consumer(state):
        for _ in range(2):
            item = state["ring"].take(timeout=None)
            state["got"].append(item)

    def check(state):
        got = state["got"]
        if sorted(x for x in got if x is not None) != ["p0", "p1"]:
            raise SchedViolation(
                f"handoff lost/tore a message: consumer saw {got!r} "
                "(want p0 and p1, each exactly once)")

    return Scenario(
        "handoff-mpmc",
        setup, [producer("p0"), producer("p1"), consumer], check,
        instrument=[_module_file(_handoff), _mutants_file()])


def _scheduler_scenario() -> Scenario:
    """The REAL ``DecodeScheduler._boundary`` races ``submit`` and a
    client ``cancel`` — the admission edge the ``_lock``/``_kick`` pair
    guards. Invariant: no sequence is ever lost (every submit is waiting,
    running, or terminally answered) and the boundary never throws."""
    from tpurpc.serving import scheduler as _smod

    class _Model:
        def prefill(self, prompts):
            import numpy as np

            states = [np.zeros(1, dtype=np.int32) for _ in prompts]
            tokens = [int(p[-1]) + 1 for p in prompts]
            return states, tokens

        def step(self, states, tokens):
            return states, [int(t) + 1 for t in tokens]

    def setup(sched: _Scheduler):
        orig_loop = _smod.DecodeScheduler._step_loop
        _smod.DecodeScheduler._step_loop = lambda self: None
        try:
            s = _smod.DecodeScheduler(
                _Model(), max_batch=4, max_waiting=16,
                idle_wait_s=0.01, name="sched-explore")
        finally:
            _smod.DecodeScheduler._step_loop = orig_loop
        first = s.submit([1, 2], max_tokens=4)
        return {"s": s, "first": first, "streams": [first], "late": []}

    def boundary(state):
        alive = state["s"]._boundary()
        assert alive, "boundary reported closed on a live scheduler"

    def submitter(state):
        stream = state["s"].submit([3, 4], max_tokens=4)
        state["late"].append(stream)

    def canceller(state):
        state["first"].cancel()

    def check(state):
        s = state["s"]
        live = {q.sid for q in s._running} | {q.sid for q in s._waiting} \
            | {q.sid for q in s._swapped}
        for stream in state["streams"] + state["late"]:
            seq = stream._seq
            if seq.sid in live:
                continue
            if seq.cancelled or not seq.q.empty():
                continue  # terminally answered (done/error/token)
            raise SchedViolation(
                f"sequence {seq.sid} vanished: not waiting, not running, "
                "never answered — the admission edge lost a submit")

    def teardown(state):
        try:
            state["s"]._closed = True
        except Exception:
            pass

    return Scenario(
        "scheduler-admission",
        setup, [boundary, submitter, canceller], check,
        instrument=[_module_file(_smod), _mutants_file()],
        teardown=teardown)


def _rendezvous_scenario() -> Scenario:
    """Live ``RdvLink`` offer/claim/complete racing peer-death ``close``
    on the receiver — the modeled sender-death scenario, run against the
    implementation. Invariants: the transfer never hangs (deadlock-free
    by construction of the explorer), and any region still claimed when
    the link died is DISCARDED — never back on the pool free list where a
    straggling writer could corrupt a re-leased region."""
    import os

    import tpurpc.core.rendezvous as _rdv

    def setup(sched: _Scheduler):
        # keep every schedule finite and the state space tiny: no standing
        # pre-grants (their top-up loop multiplies sched points) and a
        # zero claim timeout (the loopback wiring answers claims
        # synchronously; a LOST claim must fall back immediately instead
        # of spinning the timed cond-wait loop against a 5 s deadline
        # real time never reaches under the shimmed clockless waits)
        saved = (_rdv._PREGRANT_DEPTH,
                 os.environ.get("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"))
        _rdv._PREGRANT_DEPTH = 0
        os.environ["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = "0"
        pool = _rdv.LandingPool("local", budget=8 << 20)
        links = {}

        def send_a(op, stream_id, payload):
            links["b"].on_op(op, stream_id, payload)

        def send_b(op, stream_id, payload):
            if links["b"].closed:
                raise OSError("link closed")
            links["a"].on_op(op, stream_id, payload)

        delivered = []

        def deliver(stream_id, flags, wrapper):
            delivered.append(bytes(wrapper[:8]))

        a = _rdv.RdvLink("explore-a", send_a, lambda *a: None,
                         pool_kinds=("local",), open_kinds=("local",))
        b = _rdv.RdvLink("explore-b", send_b, deliver,
                         pool_kinds=("local",), open_kinds=("local",))
        links["a"], links["b"] = a, b

        # the receiver leases from OUR scenario pool, not the global one
        def lease_local(nbytes, kinds):
            if not kinds or "local" not in kinds:
                return None
            # ownership transfers by return (the link registers it)
            return pool.lease(nbytes,  # tpr: allow(ringpool)
                              next(b._lease_ids))

        b._lease_for = lease_local
        a.negotiated = True
        b.negotiated = True
        payload = b"\xabtpurpc!" * (_rdv._MIN_CLASS // 8)
        return {"a": a, "b": b, "pool": pool, "payload": payload,
                "delivered": delivered, "death_claimed": [],
                "saved": saved}

    def sender(state):
        a = state["a"]
        payload = state["payload"]
        # fallback (False) is a legal outcome when close wins the race;
        # hanging or corrupting the pool is not
        a.send_message(1, 0, [payload], len(payload))

    def killer(state):
        b = state["b"]
        state["death_claimed"].extend(b._leases.values())
        b.close()

    def check(state):
        pool = state["pool"]
        free_regions = [pr for bucket in pool._free.values()
                        for pr in bucket]
        for lease in state["death_claimed"]:
            if lease.delivered:
                # the transfer completed before the link actually died:
                # recycling after the wrapper's death is the legal path
                continue
            if lease.pr in free_regions:
                raise SchedViolation(
                    "a region claimed-but-undelivered at link death was "
                    "returned to the pool FREE LIST instead of being "
                    "discarded — a straggling one-sided writer can corrupt "
                    "whoever leases it next")

    def teardown(state):
        try:
            state["a"].close()
            state["b"].close()
            state["pool"].trim()
        except Exception:
            pass
        depth, env = state["saved"]
        _rdv._PREGRANT_DEPTH = depth
        if env is None:
            os.environ.pop("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", None)
        else:
            os.environ["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = env

    return Scenario(
        "rendezvous-death",
        setup, [sender, killer], check,
        instrument=[_module_file(_rdv), _mutants_file()],
        teardown=teardown, max_steps=120000)


def _kv_scenario() -> Scenario:
    """Live ``KvBlockManager`` refcounts under racing release paths: one
    thread frees a table whose prompt span is shared with the prefix
    cache, the other forces a cache eviction (an allocation the arena can
    only satisfy by evicting). Invariant: after both, every span block is
    back on the free list — a lost refcount decrement strands blocks as
    phantom 'used' forever."""
    import numpy as np

    from tpurpc.serving import kv as _kv

    def setup(sched: _Scheduler):
        mgr = _kv.KvBlockManager(n_blocks=4, block_bytes=_kv.ENTRY_BYTES * 4,
                                 kind="local", name="kv-explore")
        prompt = np.arange(8, dtype=np.int32)  # span = 8 tokens = 2 blocks
        # harness-scoped tables: teardown closes the whole arena
        kv1, hit = mgr.alloc_for_prompt(1, prompt)  # tpr: allow(kv)
        assert hit == 0
        for i, tok in enumerate(prompt):
            kv1.append(i + 1, int(tok))
        # donate the span to the prefix cache: span blocks now refs=2
        mgr.free_blocks(kv1, cache_prefix=True)
        kv2, hit = mgr.alloc_for_prompt(2, prompt)
        assert hit == 8, f"prefix hit expected, got {hit}"
        return {"mgr": mgr, "kv2": kv2}

    def releaser(state):
        # drop the table's refs on the shared span (2 -> 1)
        state["mgr"].free_blocks(state["kv2"], cache_prefix=False)

    def evictor(state):
        # force the cache's refs to drop too (1 -> 0 => free), by
        # allocating more than the free list holds without eviction;
        # KvArenaFull is a legal outcome (the table's refs still pin the
        # span when this thread runs first) — the eviction itself, which
        # is the racing decrement, has happened either way
        mgr = state["mgr"]
        try:
            got = mgr.alloc_blocks(99, 3)  # tpr: allow(kv)
        except _kv.KvArenaFull:
            return
        mgr.free_blocks_raw(got)

    def check(state):
        mgr = state["mgr"]
        stats = mgr.stats()
        if stats["free"] != mgr.n_blocks or stats["used"] != 0:
            raise SchedViolation(
                "kv refcount race stranded blocks: after releasing the "
                f"table AND evicting the cache, {stats['used']} block(s) "
                f"remain phantom-used (free={stats['free']}/"
                f"{mgr.n_blocks}) — a lost decrement leaks arena memory "
                "forever")

    def teardown(state):
        try:
            state["mgr"].close()
        except Exception:
            pass

    return Scenario(
        "kv-refcount",
        setup, [releaser, evictor], check,
        instrument=[_module_file(_kv), _mutants_file()],
        teardown=teardown)


def _park_scenario() -> Scenario:
    """The live ``Pair`` park handshake racing an incoming send — the
    park-decide vs incoming-byte race ``_complete_park``'s post-ack
    re-check exists for (tpurpc-hive).  One thread initiates a park on
    an idle pair A and pumps the notify handshake to completion; the
    other pushes a payload from B into A's ring.  Invariant: whatever
    the interleaving (park aborted, parked-then-woken, NACKed), the
    payload is retrievable at A afterwards — a byte stranded in a ring
    that went back to the shared pool is the violation."""
    import tpurpc.core.pair as _pair

    def setup(sched: _Scheduler):
        a, b = _pair.create_loopback_pair(ring_size=1 << 14)
        payload = b"\xa5hive-park-race!" * 4
        return {"a": a, "b": b, "payload": payload, "sent": [0]}

    def parker(state):
        a, b = state["a"], state["b"]
        # decide to park (idle right now), then pump both notify streams
        # so the handshake progresses: B handles "p" (window close +
        # ack), A handles "q" (_complete_park — the racy completion)
        a.maybe_park(time.monotonic(), 0.0)
        if b.drain_notifications():
            b.kick()
        if a.drain_notifications():
            a.kick()

    def sender(state):
        state["sent"][0] = state["b"].send([state["payload"]])

    def check(state):
        a, b, payload = state["a"], state["b"], state["payload"]
        got = bytearray()
        # drive the episode to quiescence: every LEGAL end-state must
        # surface the payload (abort kept the rings; a wake/unpark
        # re-armed them; a NACK never parked at all)
        for _ in range(64):
            if b.drain_notifications():
                b.kick()
            if a.drain_notifications():
                a.kick()
            if state["sent"][0] < len(payload):
                state["sent"][0] += b.send([payload], state["sent"][0])
                continue
            if a._parked:
                a.unpark()
                continue
            if a.readable() or a.has_message():
                got += a.recv()
            if bytes(got) == payload:
                break
        if bytes(got) != payload:
            raise SchedViolation(
                "park lost the race payload: "
                f"{len(got)}/{len(payload)} bytes recovered "
                f"(parked={a._parked}, pending={a._park_pending}) — a "
                "byte that landed between the park decision and the "
                "peer's ack was stranded in a ring released to the pool")

    def teardown(state):
        try:
            state["a"].destroy()
            state["b"].destroy()
        except Exception:
            pass
        _pair.RingPool.reset()

    return Scenario(
        "pair-park",
        setup, [parker, sender], check,
        instrument=[_module_file(_pair), _mutants_file()],
        teardown=teardown, max_steps=200000)


def _mutants_file() -> str:
    from tpurpc.analysis import schedmutants

    return schedmutants.__file__


#: scenario name -> zero-arg factory (fresh Scenario per exploration)
SCENARIOS: Dict[str, Callable[[], Scenario]] = {
    "handoff-mpmc": _handoff_scenario,
    "scheduler-admission": _scheduler_scenario,
    "rendezvous-death": _rendezvous_scenario,
    "kv-refcount": _kv_scenario,
    "pair-park": _park_scenario,
}


# ---------------------------------------------------------------------------
# Seeded real-code mutants (the explorer's teeth).
# ---------------------------------------------------------------------------

def _mutants():
    from tpurpc.analysis import schedmutants

    return schedmutants.SCHED_MUTANTS


def run_scenario(name: str, preemption_bound: int = 2,
                 max_schedules: int = 20000,
                 mutant: Optional[str] = None) -> ExploreResult:
    """Explore one named scenario, optionally with a seeded real-code
    mutant applied for the duration (the mutant names which scenario it
    belongs to; mismatches are an error)."""
    scenario = SCENARIOS[name]()
    if mutant is None:
        return explore(scenario, preemption_bound, max_schedules)
    m = _mutants()[mutant]
    if m.scenario != name:
        raise ValueError(f"mutant {mutant} targets scenario {m.scenario}, "
                         f"not {name}")
    with m.applied():
        return explore(scenario, preemption_bound, max_schedules)


def quick_suite(preemption_bound: int = 1, max_schedules: int = 1500,
                verbose: bool = False) -> List[ExploreResult]:
    """The check.sh ``schedule-quick`` stage: every scenario explored
    clean at the given bound, every seeded mutant killed. Sized to fit a
    ~60 s budget on a 1-core rig; the full-depth runs live in
    tests/test_schedule.py."""
    out: List[ExploreResult] = []
    for name in sorted(SCENARIOS):
        res = run_scenario(name, preemption_bound, max_schedules)
        if verbose:
            print(f"schedule: {res!r}")
        out.append(res)
    for mname, m in sorted(_mutants().items()):
        res = run_scenario(m.scenario, preemption_bound, max_schedules,
                           mutant=mname)
        # a mutant result is GOOD when a violation was found
        res = ExploreResult(f"mutant:{mname}", not res.ok, res.schedules,
                            res.violation, res.steps, res.capped,
                            res.preemption_bound)
        if verbose:
            kill = "KILLED" if res.ok else "SURVIVED"
            print(f"schedule: mutant {mname}: {kill} "
                  f"({res.schedules} schedules)")
        out.append(res)
    return out


def mutant_kill_suite(preemption_bound: int = 2,
                      max_schedules: int = 20000,
                      verbose: bool = False) -> Dict[str, bool]:
    """killed-by-exploration per seeded real-code mutant (the acceptance
    gate: every one must be True, and the clean scenarios must pass)."""
    kills: Dict[str, bool] = {}
    for mname, m in sorted(_mutants().items()):
        res = run_scenario(m.scenario, preemption_bound, max_schedules,
                           mutant=mname)
        kills[mname] = res.violation is not None
        if verbose:
            print(f"schedule mutant {mname}: "
                  f"{'KILLED' if kills[mname] else 'SURVIVED'} "
                  f"({res.schedules} schedules, {res.steps} steps)")
    return kills
