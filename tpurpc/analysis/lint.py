"""tpurpc-specific AST lint passes.

Four rules, each guarding an invariant the round-5 review found violated by
hand (ISSUE 2) and that no general-purpose linter knows about:

* ``lease``    — lease pairing: a function that calls a ``*send_reserve*``
  entry point must reach ``*send_commit*`` on success and ``*send_abort*`` on
  every exception path (the abort must sit in an ``except``/``finally``), and
  the fill code between reserve and commit must be covered by that handler.
  An unaborted lease wedges the peer's ring write lock forever (the exact
  round-5 native-plane bug).
* ``copy``     — hot-path no-copy: in the data-plane modules
  (``core/ring.py``, ``core/pair.py``, ``wire/grpc_h2.py``,
  ``jaxshim/codec.py``) the patterns ``b"".join(...)``,
  ``*.from_buffer_copy(...)`` and ``bytes(x[a:b])`` / ``bytearray(x[a:b])``
  are banned: the first two hide whole-payload copies, the last double-copies
  (slicing ``bytes``/``bytearray`` copies once, materializing again copies
  twice). The sanctioned escape hatch is slicing a ``memoryview`` (zero-copy)
  and calling ``.tobytes()`` — one visible, greppable copy.
* ``lock``     — lock map: a class that declares ``_GUARDED_BY =
  {"attr": "_lock"}`` promises that ``self.attr`` is only MUTATED inside
  ``with self._lock:`` (``__init__`` is exempt: construction happens-before
  sharing). This is the bug class of the round-5 ``xds.py`` finding — an
  unlocked ``subscribed[:]`` mutation racing a locked snapshot.
* ``wallclock``— monotonic clocks: ``time.time()`` is banned for anything
  that could feed duration/interval math; genuinely absolute timestamps
  (channelz report fields, human-facing log stamps) carry an explicit
  ``# tpr: allow(wallclock)`` annotation.
* ``block``    — no unbounded blocking on the inline dispatch path
  (``rpc/server.py``, the functions the reactor invocation from
  ``_ServerSink.commit`` runs on the connection READER thread —
  ``INLINE_DISPATCH_PATH``): ``time.sleep`` and timeout-less
  ``.acquire()`` / ``.get()`` / ``.wait()`` / ``.join()`` stall every
  stream on the connection. Bounded-slice waits (an explicit timeout)
  pass; deliberate exceptions carry ``# tpr: allow(block)``.
* ``log``      — hot-path modules (``core/ring.py``, ``core/pair.py``,
  ``core/poller.py``, ``wire/grpc_h2.py``) may only call ``log_debug`` /
  ``log_info`` behind a ``TraceFlag`` guard — ``flag.log(...)`` (which
  tests ``enabled`` first) or ``if flag:`` / ``if flag.enabled:`` — so
  %-formatting and string building never run on the fast path when
  tracing is off. ``log_error`` is exempt (error paths are cold by
  definition). Deliberate exceptions carry ``# tpr: allow(log)``.
* ``shard``    — shard confinement (tpurpc-manycore, ISSUE 7): in modules
  where a class declares ``_MERGE_BOUNDARY = ("fn", ...)``, any attribute
  named in any class's ``_GUARDED_BY`` is shard-local state — mutating it
  through a non-``self`` base (another shard's queue, a sub-batch's result
  slot) is a cross-shard write, allowed ONLY inside the declared merge-
  boundary functions. Per-core shards meet at exactly one place; the rule
  keeps it that way. Deliberate exceptions carry ``# tpr: allow(shard)``.
* ``flight``   — flight-recorder emission sites in the same hot modules
  must use the preallocated event encoder as designed: arguments to
  ``*flight*.emit(...)`` may be names, attributes, numeric constants and
  arithmetic over them — never dict/list/set/tuple displays, f-strings,
  string/bytes constants, comprehensions, or nested CALLS (a ``str()``,
  ``format()``, ``tag_for()`` or even ``len()`` in the argument list is
  per-event work the always-on recorder must not pay; precompute the int
  on a cold path). Deliberate exceptions carry ``# tpr: allow(flight)``.
* ``stage``    — tpurpc-lens (ISSUE 8) attribution plumbing, two halves.
  (a) Frame-marker / hop registrations are STATIC module-level constants:
  ``profiler.register_stages(...)`` and ``lens.hop_counters(...)`` calls
  must sit at module level (the sampler reads the registry lock-free, so
  it must be fully populated at import and never mutate at runtime), with
  ``register_stages`` taking ``__file__``/a string literal plus a dict of
  string constants (literal or a module-level ``_LENS_STAGES`` constant)
  and ``hop_counters`` a declared-hop string literal — no dynamic
  strings. (b) Waterfall hop accounting sites — ``.inc(...)`` on a
  ``_LENS_*``-bound counter — run per batched op on the data plane and
  must use the same pure-int plumbing the ``flight`` rule enforces: names,
  attributes and arithmetic only, no calls/displays/str constants.
  Deliberate exceptions carry ``# tpr: allow(stage)``.

* ``kv``       — KV block-alloc pairing (tpurpc-keystone, ISSUE 11): a
  function that calls ``*alloc_blocks*`` / ``*alloc_for_prompt*`` must
  reach a ``*free_blocks*`` / ``*swap_out*`` / ``*quarantine*`` /
  ``*release_kv*`` on an exception path (except/finally) — a raise
  between alloc and ownership hand-off leaks arena blocks (device
  memory) forever. ``# tpr: allow(kv)`` marks same-statement ownership
  transfers.

* ``rawlock``  — factory-made locks (tpurpc-proof, ISSUE 12): in a module
  that imports ``make_lock``/``make_rlock``/``make_condition`` from
  :mod:`tpurpc.analysis.locks`, constructing ``threading.Lock()`` /
  ``threading.RLock()`` / ``threading.Condition()`` directly is a blind
  spot — the raw primitive escapes both ``TPURPC_DEBUG_LOCKS`` lock-order
  checking and the deterministic schedule explorer's factory seam. Route
  it through the factory with a ``Class._attr`` name, or carry
  ``# tpr: allow(rawlock)`` where the raw primitive is the point (the
  checked-lock implementation itself, post-fork singleton rebuilds).

* ``tpr-obs``  — the C emission macro (tpurpc-xray, ISSUE 19): the
  ``flight`` rule's discipline, extended to the native plane's
  ``TPR_OBS(kEv..., tag, a1, a2)`` sites in ``native/src``. Text-based
  (no C AST here): the event code must be a static ``kEv*`` constant,
  the tag a pre-interned variable (``tag_for(...)`` in the argument
  list interns per event — cold-path work on the hot path), arguments
  carry no string/char literals and no function calls (the same
  precompute-the-int contract), and raw ``tpr_obs::emit(...)`` outside
  the plane's own implementation bypasses the macro's ``enabled()``
  guard. Checked by :func:`lint_native_source` /
  :func:`lint_native_tree` (the CLI's default pass includes it);
  deliberate exceptions carry ``// tpr: allow(tpr-obs)``.

* ``diag``     — read-only diagnosis (tpurpc-oracle, ISSUE 20): the
  evidence-rule functions in ``obs/diagnose.py`` (``_collect_*`` /
  ``_score_*``) may only READ the telemetry planes. A counter bump, a
  flight emit, a trip, a capture, or a tag intern from inside a
  diagnosis mutates the very evidence the next diagnosis reads — the
  observer effect as a bug class. Banned callee names inside those
  functions: ``inc``/``dec``/``set``/``observe``/``record``/``emit``/
  ``capture``/``external_trip``/``tag_for``/``sample_once``/``reset``/
  ``clamp``. Deliberate exceptions carry ``# tpr: allow(diag)``.

Suppression grammar: a line comment ``# tpr: allow(<rule>)`` disables that
rule for its line. The hot-path modules are expected to carry NO ``copy``
suppressions — a copy on the data plane is either fixed or it is a finding.
Suppressions are themselves audited (:func:`audit_suppressions`): an
``allow(rule)`` whose rule would NOT fire on that line with suppressions
disabled is stale and reported as a ``suppress`` violation — dead
annotations accrete into camouflage for real ones.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: repo-relative suffixes of the modules under the no-copy rules
HOT_COPY_MODULES = (
    os.path.join("tpurpc", "core", "ring.py"),
    os.path.join("tpurpc", "core", "pair.py"),
    os.path.join("tpurpc", "wire", "grpc_h2.py"),
    os.path.join("tpurpc", "jaxshim", "codec.py"),
)

#: repo-relative suffixes of the modules under the guarded-logging rule:
#: the data plane's per-message/per-scan code, where an unguarded
#: log_debug("%s", x) pays its string formatting even with tracing off
HOT_LOG_MODULES = (
    os.path.join("tpurpc", "core", "ring.py"),
    os.path.join("tpurpc", "core", "pair.py"),
    os.path.join("tpurpc", "core", "poller.py"),
    os.path.join("tpurpc", "wire", "grpc_h2.py"),
)

#: modules whose flight-recorder emission sites must stay on the
#: preallocated-encoder discipline (ISSUE 5 — the recorder is ALWAYS on,
#: so any per-event construction here is a permanent hot-path tax).
#: tpurpc-fleet (ISSUE 6) extends the rule to the fleet plumbing: the
#: hedge / drain / admission / subchannel-ejection emission sites in the
#: channel, server, and resolver run per-RPC or per-pick — same
#: discipline, interned tags, pure-int args.
FLIGHT_HOT_MODULES = HOT_LOG_MODULES + (
    os.path.join("tpurpc", "rpc", "channel.py"),
    os.path.join("tpurpc", "rpc", "server.py"),
    os.path.join("tpurpc", "rpc", "resolver.py"),
    # tpurpc-express (ISSUE 9): rendezvous emission sites run per solicited
    # bulk transfer — interned link tags, pure-int args
    os.path.join("tpurpc", "core", "rendezvous.py"),
    # tpurpc-hive (ISSUE 16): the accept path emits ACCEPT_SHED at storm
    # rate — one interned listener tag, two precomputed ints, per shed
    os.path.join("tpurpc", "core", "endpoint.py"),
    # tpurpc-cadence (ISSUE 10): the decode scheduler emits on the step
    # loop — once per device step and at membership edges, but the step
    # cadence can be kHz, so the same discipline applies: interned
    # scheduler tag, precomputed int locals, nothing allocated per emit
    os.path.join("tpurpc", "serving", "scheduler.py"),
    # tpurpc-keystone (ISSUE 11): the KV plane emits at alloc/free/swap/
    # handoff edges — per-sequence, but a preemption storm makes that a
    # high-rate path; same pure-int discipline
    os.path.join("tpurpc", "serving", "kv.py"),
    os.path.join("tpurpc", "serving", "disagg.py"),
    # tpurpc-pulse (ISSUE 13): descriptor-ring emission sites run at
    # adoption/flip/stall edges on the control hot path — same pure-int
    # discipline, interned plane tag
    os.path.join("tpurpc", "core", "ctrlring.py"),
    # tpurpc-argus (ISSUE 14): the tsdb sample tick and the slo evaluator
    # run forever on background cadences, and the bundle/collector planes
    # emit lifecycle events — every flight emission site stays on the
    # interned-tag pure-int discipline (the tsdb sample path itself is
    # additionally alloc-audited by its preallocated-ring design)
    os.path.join("tpurpc", "obs", "tsdb.py"),
    os.path.join("tpurpc", "obs", "slo.py"),
    os.path.join("tpurpc", "obs", "bundle.py"),
    os.path.join("tpurpc", "obs", "collector.py"),
    # tpurpc-oracle (ISSUE 20): the diagnosis engine is read-only by
    # contract (the `diag` rule) — but keeping it under the flight
    # pure-int discipline means any future emission site added here
    # inherits the interned-tag contract instead of silently regressing
    os.path.join("tpurpc", "obs", "diagnose.py"),
)

#: module suffix -> qualified functions on its INLINE DISPATCH path (the
#: reactor invocation from _ServerSink.commit: these run on the connection
#: reader thread, where an unbounded block stalls every stream on the
#: connection — ISSUE 3's no-block-in-dispatch rule). The `block` rule
#: forbids time.sleep and timeout-less .acquire()/.get()/.wait()/.join()
#: inside them; bounded-slice waits (an explicit timeout) pass, and a
#: deliberate exception carries an allow(block) annotation.
INLINE_DISPATCH_PATH: Dict[str, Tuple[str, ...]] = {
    os.path.join("tpurpc", "rpc", "server.py"): (
        "_ServerSink.commit",
        "_ServerStream.commit_message",
        "_ServerStream.commit_external",
        "_ServerStream._acquire_credit",
        "_ServerStream._release_credit",
        "_ServerStream.next_request",
        "_ServerConnection._claim_inline",
        "_ServerConnection._run_inline",
        "_ServerConnection._run_handler",
        "_ServerConnection._run_handler_inner",
        "_ServerConnection._send_trailers",
        "_ServerConnection._finish_stream",
        "_ServerConnection._rdv_deliver",
    ),
    # tpurpc-cadence (ISSUE 10): the decode STEP LOOP is the serving
    # plane's reader-thread analog — every running stream stalls behind
    # it, so it must never hold a timeout-less lock or park unbounded
    # (its idle wait is a bounded condition slice; submit kicks it early)
    os.path.join("tpurpc", "serving", "scheduler.py"): (
        "DecodeScheduler._step_loop",
        "DecodeScheduler._boundary",
        "DecodeScheduler._admit",
        "DecodeScheduler._prefill_batch",
        "DecodeScheduler._run_step",
    ),
    # tpurpc-oracle (ISSUE 20): the diagnosis engine runs inside scrape
    # dispatch, watchdog trip hooks, and the bundle writer — a diagnosis
    # that parks unbounded wedges the very sweep that called it
    os.path.join("tpurpc", "obs", "diagnose.py"): (
        "detect_onset",
        "series_shifts",
        "find_symptom",
        "diagnose",
        "diagnose_doc",
        "_combine",
    ),
}

#: the CROSS-PROCESS modules (ISSUE 17): every wire effect these emit —
#: a framed send, a peer-ring post, a one-sided landing, a wakeup kick —
#: must leave through ``tpurpc.core.transport.dispatch``, the seam the
#: simnet simulator (and any future fault injector) hooks.  A raw
#: primitive called around the seam is an effect message-level
#: exploration can never reorder, drop, or partition — a hole in the
#: checked protocol surface.
XPROC_MODULES = (
    os.path.join("tpurpc", "core", "pair.py"),
    os.path.join("tpurpc", "core", "rendezvous.py"),
    os.path.join("tpurpc", "core", "ctrlring.py"),
    os.path.join("tpurpc", "serving", "disagg.py"),
)

#: send-side raw-primitive name keywords: a ``*_raw`` callee whose name
#: carries one of these is a wire send (``_drain_raw`` and friends are
#: receive-side — local reads of the process's own ring/socket)
_XPROC_SEND_WORDS = ("notify", "send", "frame", "post", "write", "kick")

#: method names whose call on a guarded attribute counts as a mutation
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft", "popitem",
    "remove", "clear", "update", "add", "discard", "setdefault", "sort",
})

_ALLOW_RE = re.compile(r"#\s*tpr:\s*allow\(([a-z_,\s]+)\)")

#: every rule an ``allow(...)`` may name (the suppression audit flags
#: unknown names too — a typo'd rule suppresses nothing forever)
KNOWN_RULES = frozenset({
    "lease", "copy", "lock", "wallclock", "block", "log", "shard",
    "flight", "stage", "rdv", "kv", "rawlock", "ringpool", "xproc",
    "diag",
})

#: suppression-audit mode: when True, ``_allowed_rules`` answers empty —
#: the audit re-lints with suppressions void to learn which would fire
_AUDIT_IGNORE_SUPPRESSIONS = False


class LintViolation:
    __slots__ = ("path", "line", "col", "rule", "message")

    def __init__(self, path: str, line: int, col: int, rule: str,
                 message: str):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message

    def __repr__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    __str__ = __repr__


def _allowed_rules(source_lines: Sequence[str], line: int) -> Set[str]:
    """Rules suppressed on ``line`` (1-based) via ``# tpr: allow(rule)``."""
    if _AUDIT_IGNORE_SUPPRESSIONS:
        return set()
    if 1 <= line <= len(source_lines):
        m = _ALLOW_RE.search(source_lines[line - 1])
        if m:
            return {tok.strip() for tok in m.group(1).split(",")}
    return set()


def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._tpr_parent = node  # type: ignore[attr-defined]


def _ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_tpr_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_tpr_parent", None)


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> Optional[str]:
    """``self.X`` / ``cls.X`` → ``X`` (optionally requiring ``X == attr``)."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        if attr is None or node.attr == attr:
            return node.attr
    return None


# -- rule: wallclock ---------------------------------------------------------

def _check_wallclock(tree: ast.AST, path: str,
                     lines: Sequence[str]) -> List[LintViolation]:
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "time"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "time"):
            if "wallclock" in _allowed_rules(lines, node.lineno):
                continue
            out.append(LintViolation(
                path, node.lineno, node.col_offset, "wallclock",
                "time.time() is not monotonic: use time.monotonic() for "
                "durations/intervals, or annotate a genuinely absolute "
                "timestamp with '# tpr: allow(wallclock)'"))
    return out


# -- rule: copy --------------------------------------------------------------

def _check_copy(tree: ast.AST, path: str,
                lines: Sequence[str]) -> List[LintViolation]:
    out = []
    for node in ast.walk(tree):
        viol = None
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "join"
                    and isinstance(f.value, ast.Constant)
                    and isinstance(f.value.value, bytes)):
                viol = ("b\"\".join() gathers with a hidden whole-payload "
                        "copy: encode into a preallocated buffer or pass the "
                        "segment list through (gather writes)")
            elif (isinstance(f, ast.Attribute)
                  and f.attr == "from_buffer_copy"):
                viol = ("from_buffer_copy duplicates the payload: use "
                        "from_buffer / a memoryview over the source")
            elif (isinstance(f, ast.Name) and f.id in ("bytes", "bytearray")
                  and len(node.args) == 1
                  and isinstance(node.args[0], ast.Subscript)
                  and isinstance(node.args[0].slice, ast.Slice)):
                viol = (f"{f.id}(x[a:b]) double-copies when x is "
                        "bytes/bytearray: slice a memoryview (zero-copy) "
                        "and .tobytes() if you truly need to materialize")
        if viol is None:
            continue
        if "copy" in _allowed_rules(lines, node.lineno):
            continue
        out.append(LintViolation(path, node.lineno, node.col_offset,
                                 "copy", viol))
    return out


# -- rule: block -------------------------------------------------------------

def _block_violation(node: ast.Call) -> Optional[str]:
    """Why this call is an unbounded block, or None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    kw = {k.arg for k in node.keywords}
    if (f.attr == "sleep" and isinstance(f.value, ast.Name)
            and f.value.id == "time"):
        return "time.sleep() parks the reader thread"
    if f.attr == "acquire" and not node.args and not (
            kw & {"timeout", "blocking"}):
        return ".acquire() with no timeout can park forever"
    if f.attr == "get" and not node.args and "timeout" not in kw:
        return ".get() with no timeout can park forever"
    if f.attr == "wait" and not node.args and "timeout" not in kw:
        return ".wait() with no timeout can park forever"
    if f.attr == "join" and not node.args and "timeout" not in kw:
        return ".join() with no timeout can park forever"
    return None


def _check_block(tree: ast.AST, path: str, lines: Sequence[str],
                 functions: "frozenset[str]") -> List[LintViolation]:
    """Forbid unbounded blocking calls inside the named functions (the
    inline-dispatch path: they run on the connection reader thread)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        parent = getattr(node, "_tpr_parent", None)
        qual = (f"{parent.name}.{node.name}"
                if isinstance(parent, ast.ClassDef) else node.name)
        if qual not in functions:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            why = _block_violation(call)
            if why is None:
                continue
            if "block" in _allowed_rules(lines, call.lineno):
                continue
            out.append(LintViolation(
                path, call.lineno, call.col_offset, "block",
                f"{qual} is on the inline dispatch path (runs on the "
                f"connection reader thread) and {why}: every stream on the "
                "connection stalls behind it — bound the wait with a "
                "timeout or move the work to the pool; a deliberate "
                "exception carries '# tpr: allow(block)'"))
    return out


# -- rule: log ---------------------------------------------------------------

_HOT_LOG_CALLS = frozenset({"log_debug", "log_info"})


def _is_flag_guard(test: ast.AST) -> bool:
    """Does this ``if`` test reference a TraceFlag? Convention-based: a
    name/attribute starting with ``trace_`` (every flag instance in the
    tree), a bare ``flag``/``*_flag`` binding, or an ``.enabled`` read."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and (
                node.id.startswith("trace_") or node.id == "flag"
                or node.id.endswith("_flag")):
            return True
        if isinstance(node, ast.Attribute) and (
                node.attr.startswith("trace_") or node.attr == "enabled"
                or node.attr.endswith("_flag")):
            return True
    return False


def _check_log(tree: ast.AST, path: str,
               lines: Sequence[str]) -> List[LintViolation]:
    """Guarded logging on the hot paths: ``log_debug``/``log_info`` must
    sit inside ``if <TraceFlag>:`` (or use ``flag.log(...)``, which never
    matches here — ``.log`` is a method name, not these functions)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else "")
        if name not in _HOT_LOG_CALLS:
            continue
        if any(isinstance(anc, ast.If) and _is_flag_guard(anc.test)
               for anc in _ancestors(node)):
            continue
        if "log" in _allowed_rules(lines, node.lineno):
            continue
        out.append(LintViolation(
            path, node.lineno, node.col_offset, "log",
            f"{name}() on a hot-path module without a TraceFlag guard: "
            "its string formatting runs even with tracing off — use "
            "flag.log(...) or wrap in 'if <trace_flag>:'; a deliberate "
            "exception carries '# tpr: allow(log)'"))
    return out


# -- rule: flight -------------------------------------------------------------

#: node types allowed inside a flight-emit argument: plain value reads and
#: integer arithmetic over them — nothing that allocates or calls
_FLIGHT_BANNED = (ast.Dict, ast.Set, ast.List, ast.Tuple, ast.JoinedStr,
                  ast.FormattedValue, ast.Call, ast.ListComp, ast.SetComp,
                  ast.DictComp, ast.GeneratorExp, ast.Lambda, ast.Starred)


def _is_flight_emit(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "emit":
        base = f.value
        if isinstance(base, ast.Name) and "flight" in base.id.lower():
            return True
        if isinstance(base, ast.Attribute) and "flight" in base.attr.lower():
            return True  # e.g. flight.RECORDER.emit — RECORDER's owner
        # RECORDER.emit / self._recorder.emit shapes
        if isinstance(base, ast.Name) and "recorder" in base.id.lower():
            return True
        if (isinstance(base, ast.Attribute)
                and "recorder" in base.attr.lower()):
            return True
    if isinstance(f, ast.Name) and "flight_emit" in f.id:
        return True
    return False


def _flight_arg_violation(arg: ast.AST) -> Optional[str]:
    for node in ast.walk(arg):
        if isinstance(node, _FLIGHT_BANNED):
            return (f"builds a {type(node).__name__} per event")
        if isinstance(node, ast.Constant) and isinstance(
                node.value, (str, bytes)):
            return "passes a str/bytes constant (events carry ints; "\
                   "intern strings once with tag_for on a cold path)"
    return None


def _check_flight(tree: ast.AST, path: str,
                  lines: Sequence[str]) -> List[LintViolation]:
    """Flight-recorder emission sites must be pure int plumbing: the
    recorder is ALWAYS on, so allocation/calls in an emit argument are a
    permanent per-event cost the preallocated encoder exists to avoid."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_flight_emit(node):
            continue
        if "flight" in _allowed_rules(lines, node.lineno):
            continue
        args = list(node.args) + [k.value for k in node.keywords]
        for arg in args:
            why = _flight_arg_violation(arg)
            if why is None:
                continue
            out.append(LintViolation(
                path, node.lineno, node.col_offset, "flight",
                f"flight emit argument {why}: the always-on recorder's "
                "hot path must stay on the preallocated encoder — "
                "precompute ints (tag_for at connect time, lengths on the "
                "cold path); a deliberate exception carries "
                "'# tpr: allow(flight)'"))
            break
    return out


# -- rule: stage -------------------------------------------------------------

def _module_consts(tree: ast.AST) -> Dict[str, ast.AST]:
    """Top-level ``NAME = <expr>`` bindings (the constants registrations
    may reference)."""
    out: Dict[str, ast.AST] = {}
    for stmt in getattr(tree, "body", ()):
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            out[stmt.targets[0].id] = stmt.value
    return out


def _static_str_dict(node: Optional[ast.AST],
                     consts: Dict[str, ast.AST]) -> bool:
    """Is ``node`` a dict of string constants — directly or via a
    module-level constant Name?"""
    if isinstance(node, ast.Name):
        node = consts.get(node.id)
    if not isinstance(node, ast.Dict):
        return False
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return False
        if not (isinstance(v, ast.Constant) and isinstance(v.value, str)):
            return False
    return True


def _check_stage(tree: ast.AST, path: str,
                 lines: Sequence[str]) -> List[LintViolation]:
    """tpurpc-lens (ISSUE 8): static stage/hop registrations + pure-int
    hop accounting. See the module docstring's ``stage`` entry."""
    out = []
    consts = _module_consts(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "register_stages":
            if "stage" in _allowed_rules(lines, node.lineno):
                continue
            if _enclosing_fn(node) is not None:
                out.append(LintViolation(
                    path, node.lineno, node.col_offset, "stage",
                    "register_stages inside a function: frame-marker "
                    "registrations must be module-level (the sampler reads "
                    "the registry lock-free — populate it at import, never "
                    "at runtime); a deliberate exception carries "
                    "'# tpr: allow(stage)'"))
                continue
            args = list(node.args)
            a0_ok = len(args) >= 1 and (
                (isinstance(args[0], ast.Name) and args[0].id == "__file__")
                or (isinstance(args[0], ast.Constant)
                    and isinstance(args[0].value, str)))
            a1_ok = len(args) >= 2 and _static_str_dict(args[1], consts)
            if not (a0_ok and a1_ok):
                out.append(LintViolation(
                    path, node.lineno, node.col_offset, "stage",
                    "register_stages arguments must be static: __file__ "
                    "(or a string literal) plus a dict of string constants "
                    "— a module-level _LENS_STAGES constant or a literal; "
                    "dynamic strings make the frame registry unauditable; "
                    "a deliberate exception carries '# tpr: allow(stage)'"))
        elif name == "hop_counters":
            if "stage" in _allowed_rules(lines, node.lineno):
                continue
            bad = _enclosing_fn(node) is not None
            bad = bad or not (node.args
                              and isinstance(node.args[0], ast.Constant)
                              and isinstance(node.args[0].value, str))
            if bad:
                out.append(LintViolation(
                    path, node.lineno, node.col_offset, "stage",
                    "hop_counters must bind a declared hop at module level "
                    "with a string-literal hop name (the cached-counter "
                    "contract: sites pay only the bump); a deliberate "
                    "exception carries '# tpr: allow(stage)'"))
        elif name == "inc":
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id.startswith("_LENS_")):
                continue
            if "stage" in _allowed_rules(lines, node.lineno):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                why = _flight_arg_violation(arg)
                if why is None:
                    continue
                out.append(LintViolation(
                    path, node.lineno, node.col_offset, "stage",
                    f"waterfall hop accounting argument {why}: hop "
                    "counters bump per batched op on the data plane — "
                    "precompute the int (the flight rule's contract); a "
                    "deliberate exception carries '# tpr: allow(stage)'"))
                break
    return out


# -- rule: lock --------------------------------------------------------------

def _guarded_by_decl(cls: ast.ClassDef) -> Dict[str, Tuple[str, ...]]:
    """Parse a class-level ``_GUARDED_BY = {"attr": "_lock" | ("_a","_b")}``."""
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_GUARDED_BY"
                and isinstance(stmt.value, ast.Dict)):
            decl: Dict[str, Tuple[str, ...]] = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    decl[k.value] = (v.value,)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    locks = tuple(e.value for e in v.elts
                                  if isinstance(e, ast.Constant)
                                  and isinstance(e.value, str))
                    if locks:
                        decl[k.value] = locks
            return decl
    return {}


def _with_holds(node: ast.AST, locks: Tuple[str, ...]) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>:`` for a lock in
    ``locks``? (``with self._cv`` counts for the condition's own lock.)"""
    for anc in _ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                # with self._lock: / with self._lock.something(): not counted
                name = _is_self_attr(expr)
                if name is None and isinstance(expr, ast.Call):
                    # e.g. `with self._lock_for(x):` — not a declared guard
                    continue
                if name in locks:
                    return True
    return False


def _mutation_target(node: ast.AST) -> Optional[ast.AST]:
    """The ``self.attr`` expression this statement mutates, if any."""
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Tuple):
                for e in t.elts:
                    got = _mutation_target_expr(e)
                    if got is not None:
                        return got
            got = _mutation_target_expr(t)
            if got is not None:
                return got
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            got = _mutation_target_expr(t)
            if got is not None:
                return got
    elif isinstance(node, ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                and _is_self_attr(f.value) is not None):
            return f.value
    return None


def _mutation_target_expr(t: ast.AST) -> Optional[ast.AST]:
    # self.attr = ... / self.attr[...] = ... / self.attr[:] = ...
    if _is_self_attr(t) is not None:
        return t
    if isinstance(t, ast.Subscript) and _is_self_attr(t.value) is not None:
        return t.value
    return None


def _check_locks(tree: ast.AST, path: str,
                 lines: Sequence[str]) -> List[LintViolation]:
    out = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        decl = _guarded_by_decl(cls)
        if not decl:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue  # construction happens-before sharing
            for node in ast.walk(fn):
                tgt = _mutation_target(node)
                if tgt is None:
                    continue
                attr = _is_self_attr(tgt)
                if attr not in decl:
                    continue
                if _with_holds(node, decl[attr]):
                    continue
                if "lock" in _allowed_rules(lines, node.lineno):
                    continue
                out.append(LintViolation(
                    path, node.lineno, node.col_offset, "lock",
                    f"{cls.name}.{attr} is declared guarded by "
                    f"{'/'.join(decl[attr])} but is mutated outside "
                    f"'with self.{decl[attr][0]}:' (in {fn.name})"))
    return out


# -- rule: shard -------------------------------------------------------------

def _merge_boundary_decl(cls: ast.ClassDef) -> Optional[Tuple[str, ...]]:
    """Parse a class-level ``_MERGE_BOUNDARY = ("fn", ...)`` declaration."""
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_MERGE_BOUNDARY"
                and isinstance(stmt.value, (ast.Tuple, ast.List))):
            return tuple(e.value for e in stmt.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
    return None


def _attr_mutation_target(node: ast.AST) -> Optional[ast.Attribute]:
    """The ``<expr>.attr`` an Assign/AugAssign/Delete/mutator-call mutates,
    for ANY base expression (the cross-instance analog of
    :func:`_mutation_target`, which only matches ``self``)."""
    def as_attr(t: ast.AST) -> Optional[ast.Attribute]:
        if isinstance(t, ast.Attribute):
            return t
        if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Attribute):
            return t.value
        return None

    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Tuple):
                for e in t.elts:
                    got = as_attr(e)
                    if got is not None:
                        return got
            got = as_attr(t)
            if got is not None:
                return got
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            got = as_attr(t)
            if got is not None:
                return got
    elif isinstance(node, ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                and isinstance(f.value, ast.Attribute)):
            return f.value
    return None


def _check_shard(tree: ast.AST, path: str,
                 lines: Sequence[str]) -> List[LintViolation]:
    """tpurpc-manycore (ISSUE 7): shard-confinement of guarded state.

    Armed only in modules where some class declares ``_MERGE_BOUNDARY =
    ("fn", ...)`` — a shard/merger module. There, any attribute listed in
    ANY class's ``_GUARDED_BY`` is shard-local state: mutating it through a
    base other than ``self`` (``other_shard._queue.append``,
    ``sub.out = ...``) is a cross-shard mutation, legal ONLY inside a
    function named in a ``_MERGE_BOUNDARY`` — the single place shards are
    allowed to meet. Everything else is the hot path, where cross-shard
    writes are exactly the coupling the per-core design forbids.
    Deliberate exceptions carry ``# tpr: allow(shard)``."""
    boundary: Set[str] = set()
    guarded: Dict[str, str] = {}  # attr -> declaring class (for the message)
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        mb = _merge_boundary_decl(cls)
        if mb is not None:
            boundary.update(mb)
        for attr in _guarded_by_decl(cls):
            guarded.setdefault(attr, cls.name)
    if not boundary or not guarded:
        return []
    out = []
    for node in ast.walk(tree):
        tgt = _attr_mutation_target(node)
        if tgt is None or tgt.attr not in guarded:
            continue
        if isinstance(tgt.value, ast.Name) and tgt.value.id in ("self", "cls"):
            continue  # shard-local mutation: the lock map's jurisdiction
        fn = _enclosing_fn(node)
        if fn is not None and getattr(fn, "name", None) in boundary:
            continue
        if "shard" in _allowed_rules(lines, node.lineno):
            continue
        out.append(LintViolation(
            path, node.lineno, node.col_offset, "shard",
            f"cross-shard mutation of {guarded[tgt.attr]}.{tgt.attr} "
            f"(guarded shard-local state) outside the merge boundary "
            f"{sorted(boundary)} — shards may only meet at the declared "
            "boundary; a deliberate exception carries '# tpr: allow(shard)'"))
    return out


# -- rule: rawlock -----------------------------------------------------------

_LOCK_FACTORIES = frozenset({"make_lock", "make_rlock", "make_condition"})
_RAW_PRIMITIVES = frozenset({"Lock", "RLock", "Condition"})


def _imports_lock_factory(tree: ast.AST) -> bool:
    """Does this module import any lock factory from analysis.locks?"""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if not mod.endswith("locks"):
                continue
            if any(alias.name in _LOCK_FACTORIES for alias in node.names):
                return True
    return False


def _check_rawlock(tree: ast.AST, path: str,
                   lines: Sequence[str]) -> List[LintViolation]:
    """tpurpc-proof (ISSUE 12): in a module that already imports the lock
    factory, a raw ``threading.Lock()``/``RLock()``/``Condition()`` is a
    verification blind spot — it dodges TPURPC_DEBUG_LOCKS *and* the
    schedule explorer's factory seam. The decode loop ran unwatched for
    two PRs exactly this way."""
    if not _imports_lock_factory(tree):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _RAW_PRIMITIVES
                and isinstance(f.value, ast.Name)
                and f.value.id == "threading"):
            continue
        if "rawlock" in _allowed_rules(lines, node.lineno):
            continue
        factory = {"Lock": "make_lock", "RLock": "make_rlock",
                   "Condition": "make_condition"}[f.attr]
        out.append(LintViolation(
            path, node.lineno, node.col_offset, "rawlock",
            f"raw threading.{f.attr}() in a module that imports the lock "
            f"factory: TPURPC_DEBUG_LOCKS and the schedule explorer never "
            f"see it — use {factory}(\"Class._attr\"); a deliberate "
            "exception carries '# tpr: allow(rawlock)'"))
    return out


# -- the suppression audit ----------------------------------------------------

def find_suppressions(source: str) -> List[Tuple[int, str]]:
    """Every ``(line, rule)`` named by a real ``# tpr: allow(...)``
    COMMENT. Tokenized, not regexed over raw lines: docstrings and error
    messages QUOTE the grammar constantly, and quoting a suppression is
    not writing one."""
    import io
    import tokenize

    out: List[Tuple[int, str]] = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _ALLOW_RE.search(tok.string)
        if m:
            for name in m.group(1).split(","):
                name = name.strip()
                if name:
                    out.append((tok.start[0], name))
    return out


def audit_suppressions_source(source: str, path: str) -> List[LintViolation]:
    """Report stale suppressions in one module: re-lint with every
    suppression void, then flag any ``allow(rule)`` whose rule did not
    fire on that line (plus unknown rule names — a typo suppresses
    nothing forever). Stale suppressions are gate failures: they read as
    "this line is a known exception" when nothing is excepted."""
    sups = find_suppressions(source)
    if not sups:
        return []
    global _AUDIT_IGNORE_SUPPRESSIONS
    _AUDIT_IGNORE_SUPPRESSIONS = True
    try:
        fired = lint_source(source, path)
    finally:
        _AUDIT_IGNORE_SUPPRESSIONS = False
    fired_at = {(v.line, v.rule) for v in fired}
    out: List[LintViolation] = []
    for line, rule in sups:
        if rule not in KNOWN_RULES:
            out.append(LintViolation(
                path, line, 0, "suppress",
                f"suppression names unknown rule '{rule}' "
                f"(known: {', '.join(sorted(KNOWN_RULES))})"))
        elif (line, rule) not in fired_at:
            out.append(LintViolation(
                path, line, 0, "suppress",
                f"stale suppression: rule '{rule}' would not fire on this "
                "line — delete the annotation (dead allows accrete into "
                "camouflage for live ones)"))
    return out


def audit_suppressions(paths: Iterable[str]) -> List[LintViolation]:
    out: List[LintViolation] = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            out.extend(audit_suppressions_source(f.read(), p))
    return out


def audit_suppressions_tree(root: Optional[str] = None) -> List[LintViolation]:
    return audit_suppressions(_tree_paths(root))


# -- rule: lease -------------------------------------------------------------

def _calls_matching(node: ast.AST, needle: str) -> List[ast.Call]:
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Call) and needle in _call_name(n)]


def _try_aborts(try_node: ast.Try) -> bool:
    """Does this Try call ``*send_abort*`` in a handler or finally?"""
    for h in try_node.handlers:
        for stmt in h.body:
            if _calls_matching(stmt, "send_abort"):
                return True
    for stmt in try_node.finalbody:
        if _calls_matching(stmt, "send_abort"):
            return True
    return False


def _enclosing_stmt(node: ast.AST, block: List[ast.stmt]) -> Optional[ast.stmt]:
    """The statement of ``block`` that (transitively) contains ``node``."""
    chain = [node] + list(_ancestors(node))
    for stmt in block:
        if stmt in chain:
            return stmt
    return None


def _check_lease(tree: ast.AST, path: str,
                 lines: Sequence[str]) -> List[LintViolation]:
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        reserves = [c for c in _calls_matching(fn, "send_reserve")
                    if _enclosing_fn(c) is fn]
        if not reserves:
            continue
        if any("lease" in _allowed_rules(lines, r.lineno) for r in reserves):
            continue
        commits = [c for c in _calls_matching(fn, "send_commit")
                   if _enclosing_fn(c) is fn]
        aborts = [c for c in _calls_matching(fn, "send_abort")
                  if _enclosing_fn(c) is fn]
        rl = reserves[0].lineno
        if not commits:
            out.append(LintViolation(
                path, rl, reserves[0].col_offset, "lease",
                f"{fn.name} reserves a send lease but never commits it: a "
                "reserved-and-dropped lease wedges the ring write lock"))
            continue
        covered_aborts = [
            a for a in aborts
            if any(isinstance(anc, (ast.ExceptHandler,)) for anc in
                   _ancestors(a))
            or any(isinstance(anc, ast.Try) and a in
                   [d for s in anc.finalbody for d in ast.walk(s)]
                   for anc in _ancestors(a))]
        if not covered_aborts:
            out.append(LintViolation(
                path, rl, reserves[0].col_offset, "lease",
                f"{fn.name} reserves a send lease with no send_abort on any "
                "exception path (except/finally): a raise between reserve "
                "and commit leaks the lease"))
            continue
        out.extend(_check_lease_region(fn, reserves, commits, path))
    return out


def _enclosing_fn(node: ast.AST) -> Optional[ast.AST]:
    for anc in _ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
    return None


def _check_lease_region(fn, reserves, commits, path) -> List[LintViolation]:
    """Fill code strictly between reserve and commit (same statement block)
    must sit inside a Try whose handler/finally aborts — an exception raised
    while filling the reserved span must release the lease."""
    out = []
    for res in reserves:
        # locate the common block holding both the reserve and a commit
        for anc in [res] + list(_ancestors(res)):
            parent = getattr(anc, "_tpr_parent", None)
            if parent is None:
                break
            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if not (isinstance(block, list) and anc in block):
                    continue
                commit_stmts = [s for c in commits
                                for s in [_enclosing_stmt(c, block)]
                                if s is not None]
                if not commit_stmts:
                    continue
                ri = block.index(anc)
                ci = max(block.index(s) for s in commit_stmts)
                for between in block[ri + 1:ci]:
                    ok = (isinstance(between, ast.Try)
                          and _try_aborts(between))
                    ok = ok or isinstance(between, (ast.Pass, ast.Continue,
                                                    ast.Break))
                    if not ok:
                        out.append(LintViolation(
                            path, between.lineno, between.col_offset,
                            "lease",
                            f"{fn.name}: statement between send_reserve and "
                            "send_commit is not covered by a "
                            "try/except-abort — an exception here leaks the "
                            "lease"))
                return out
    return out


# -- rule: rdv (rendezvous claim pairing, tpurpc-express ISSUE 9) -------------

def _check_rdv(tree: ast.AST, path: str,
               lines: Sequence[str]) -> List[LintViolation]:
    """A function that obtains a rendezvous region claim (``*rdv_claim*``)
    must send ``*rdv_complete*`` on the success path AND cover an exception
    path (except/finally) with ``*rdv_release*`` — a claimed-and-dropped
    region pins the peer's landing pool until the connection dies (the
    lease-pairing rule's shape, lifted to the bulk-transfer plane).
    Suppression: ``# tpr: allow(rdv)`` on the claim line."""
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        claims = [c for c in _calls_matching(fn, "rdv_claim")
                  if _enclosing_fn(c) is fn]
        if not claims:
            continue
        if any("rdv" in _allowed_rules(lines, c.lineno) for c in claims):
            continue
        completes = [c for c in _calls_matching(fn, "rdv_complete")
                     if _enclosing_fn(c) is fn]
        releases = [c for c in _calls_matching(fn, "rdv_release")
                    if _enclosing_fn(c) is fn]
        cl = claims[0].lineno
        if not completes:
            out.append(LintViolation(
                path, cl, claims[0].col_offset, "rdv",
                f"{fn.name} claims a rendezvous region but never "
                "completes it: the peer's landing region stays claimed "
                "until the connection dies"))
            continue
        covered = [
            r for r in releases
            if any(isinstance(anc, ast.ExceptHandler)
                   for anc in _ancestors(r))
            or any(isinstance(anc, ast.Try) and r in
                   [d for s in anc.finalbody for d in ast.walk(s)]
                   for anc in _ancestors(r))]
        if not covered:
            out.append(LintViolation(
                path, cl, claims[0].col_offset, "rdv",
                f"{fn.name} claims a rendezvous region with no "
                "rdv_release on any exception path (except/finally): a "
                "raise between claim and complete leaks the claim"))
    return out


# -- rule: kv (block-alloc pairing, tpurpc-keystone ISSUE 11) -----------------

#: call-name fragments that RELEASE kv blocks for the `kv` rule
_KV_RELEASERS = ("free_blocks", "swap_out", "quarantine", "release_kv")


def _check_kv(tree: ast.AST, path: str,
              lines: Sequence[str]) -> List[LintViolation]:
    """A function that allocates KV blocks (``*alloc_blocks*`` /
    ``*alloc_for_prompt*``) must cover an exception path (except/finally)
    with a release — ``*free_blocks*`` / ``*swap_out*`` /
    ``*quarantine*`` / ``*release_kv*`` — or the blocks leak out of the
    arena's accounting forever (the rdv/lease pairing rule, lifted to the
    KV plane, where the leak is device memory). Ownership-transfer sites
    (the table adopts the blocks in the same statement) carry
    ``# tpr: allow(kv)`` on the alloc line."""
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        allocs = [c for c in (_calls_matching(fn, "alloc_blocks")
                              + _calls_matching(fn, "alloc_for_prompt"))
                  if _enclosing_fn(c) is fn]
        if not allocs:
            continue
        if any("kv" in _allowed_rules(lines, c.lineno) for c in allocs):
            continue
        releases = [c for frag in _KV_RELEASERS
                    for c in _calls_matching(fn, frag)
                    if _enclosing_fn(c) is fn]
        covered = [
            r for r in releases
            if any(isinstance(anc, ast.ExceptHandler)
                   for anc in _ancestors(r))
            or any(isinstance(anc, ast.Try) and r in
                   [d for s in anc.finalbody for d in ast.walk(s)]
                   for anc in _ancestors(r))]
        if not covered:
            al = allocs[0].lineno
            out.append(LintViolation(
                path, al, allocs[0].col_offset, "kv",
                f"{fn.name} allocates KV blocks with no free/swap/"
                "quarantine on any exception path (except/finally): a "
                "raise between alloc and ownership hand-off leaks arena "
                "blocks forever"))
    return out


# -- rule: ringpool (shared ring-pool lease pairing, tpurpc-hive ISSUE 16) ----

def _pool_calls(fn: ast.AST, attr: str) -> List[ast.Call]:
    """Calls ``<something-pool>.<attr>(...)`` — the receiver's source text
    must mention "pool" (``pool.lease``, ``self._pool.release``,
    ``RingPool.get().lease``), which keeps the rule off the unrelated
    ``lease``/``release`` vocabularies (KV leases, RegionLease, reader
    release)."""
    out = []
    for n in ast.walk(fn):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == attr):
            try:
                base = ast.unparse(n.func.value)
            except Exception:
                base = ""
            if "pool" in base.lower():
                out.append(n)
    return out


def _check_ringpool(tree: ast.AST, path: str,
                    lines: Sequence[str]) -> List[LintViolation]:
    """A function that leases from a shared ring pool (``pool.lease``)
    must cover an exception path (except/finally) with ``pool.release``
    — a leased-and-dropped region strands bytes in the pool's ``leased``
    accounting forever and, worse, the region itself is gone (the
    kv/rdv pairing rule, lifted to the C100K ring plane where the leak
    is the pool the whole fleet parks into). Ownership-transfer sites
    (the pair adopts the regions in the same lock scope and its
    ``_release_regions`` owns the return path) carry
    ``# tpr: allow(ringpool)`` on the lease line."""
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        leases = [c for c in _pool_calls(fn, "lease")
                  if _enclosing_fn(c) is fn]
        if not leases:
            continue
        if any("ringpool" in _allowed_rules(lines, c.lineno)
               for c in leases):
            continue
        # both return idioms pair a pool lease: RingPool's
        # ``pool.release(region)`` and the landing plane's
        # ``lease.release()`` (RegionLease returns itself to its pool)
        releases = [c for c in _pool_calls(fn, "release")
                    if _enclosing_fn(c) is fn]
        for n in ast.walk(fn):
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "release"
                    and _enclosing_fn(n) is fn):
                try:
                    base = ast.unparse(n.func.value)
                except Exception:
                    base = ""
                if "lease" in base.lower():
                    releases.append(n)
        covered = [
            r for r in releases
            if any(isinstance(anc, ast.ExceptHandler)
                   for anc in _ancestors(r))
            or any(isinstance(anc, ast.Try) and r in
                   [d for s in anc.finalbody for d in ast.walk(s)]
                   for anc in _ancestors(r))]
        if not covered:
            ln = leases[0].lineno
            out.append(LintViolation(
                path, ln, leases[0].col_offset, "ringpool",
                f"{fn.name} leases from a ring pool with no pool.release "
                "on any exception path (except/finally): a raise between "
                "lease and adoption strands the region and its "
                "leased-bytes accounting forever"))
    return out


def _xproc_raw_send(call: ast.Call) -> Optional[str]:
    """The raw-send tag of ``call`` if it is a cross-process wire effect
    invoked directly, else None.  Three shapes count: a send-side
    ``*_raw`` primitive (the designated dispatch target of a seam
    wrapper), the rendezvous ``_place`` landing closure, and a peer-ring
    window post (``tx.post(...)`` — the receiver lives in the OTHER
    process's mapped ring)."""
    func = call.func
    if isinstance(func, ast.Attribute):
        name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    else:
        return None
    if name.endswith("_raw") and any(w in name for w in _XPROC_SEND_WORDS):
        return name
    if name == "_place":
        return name
    if name == "post" and isinstance(func, ast.Attribute):
        try:
            base = ast.unparse(func.value)
        except Exception:
            base = ""
        if base == "tx" or base.endswith(".tx"):
            return f"{base}.post"
    return None


def _check_xproc(tree: ast.AST, path: str,
                 lines: Sequence[str]) -> List[LintViolation]:
    """Cross-process modules (XPROC_MODULES) must route wire effects
    through the transport seam (ISSUE 17): a raw send primitive — a
    send-side ``*_raw`` callee, the ``_place`` one-sided landing
    closure, a direct peer-ring ``tx.post`` — may be CALLED only from
    (a) a function that itself routes through ``transport.dispatch``
    (the seam wrapper, whose ``NotImplemented`` fallback is the
    un-hooked production path), or (b) another ``*_raw`` function (raw
    implementations may compose below the seam).  Anything else is a
    wire effect the simnet explorer can never see, reorder, or drop.
    Suppress deliberate pre-seam paths (the bootstrap address-exchange
    handshake) with ``# tpr: allow(xproc)``."""
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        raws = []
        dispatches = False
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call) or _enclosing_fn(n) is not fn:
                continue
            f = n.func
            cname = (f.attr if isinstance(f, ast.Attribute)
                     else f.id if isinstance(f, ast.Name) else None)
            if cname == "dispatch":
                dispatches = True
            tag = _xproc_raw_send(n)
            if tag is not None:
                raws.append((n, tag))
        if not raws or dispatches or fn.name.endswith("_raw"):
            continue
        for n, tag in raws:
            if "xproc" in _allowed_rules(lines, n.lineno):
                continue
            out.append(LintViolation(
                path, n.lineno, n.col_offset, "xproc",
                f"{fn.name} calls raw transport primitive {tag} around "
                "the transport seam: cross-process effects must leave "
                "through transport.dispatch so message-level exploration "
                "(simnet) and fault injection see every send"))
    return out


# -- rule: tpr-obs (C emission discipline, tpurpc-xray ISSUE 19) --------------

#: C-side suppression comment — ``// tpr: allow(tpr-obs)`` (the python
#: grammar's char class has no ``-``, so the C rule carries its own)
_NATIVE_ALLOW_RE = re.compile(r"//\s*tpr:\s*allow\(([a-z_\-,\s]+)\)")
_NATIVE_CODE_RE = re.compile(r"^(?:tpr_obs::)?kEv\w+$")
_NATIVE_CALL_RE = re.compile(r"\b\w+\s*\(")
#: files that ARE the obs plane — raw emit is their implementation detail
_NATIVE_OBS_IMPL = ("tpr_obs.h", "tpr_obs.cc")


def _native_allowed(lines: Sequence[str], line: int) -> bool:
    if _AUDIT_IGNORE_SUPPRESSIONS:
        return False
    if 1 <= line <= len(lines):
        m = _NATIVE_ALLOW_RE.search(lines[line - 1])
        if m:
            return "tpr-obs" in {t.strip() for t in m.group(1).split(",")}
    return False


def _native_split_args(text: str) -> List[str]:
    """Top-level comma split of a balanced C argument list."""
    out, depth, start = [], 0, 0
    for i, ch in enumerate(text):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(text[start:i])
            start = i + 1
    out.append(text[start:])
    return [a.strip() for a in out]


def lint_native_source(source: str, path: str) -> List[LintViolation]:
    """The ``tpr-obs`` rule over one C source: every ``TPR_OBS(...)``
    site must be static-tag pure-int plumbing (see the module docstring's
    ``tpr-obs`` entry), and ``tpr_obs::emit`` may only be called raw
    inside the obs plane's own implementation."""
    out: List[LintViolation] = []
    lines = source.split("\n")
    base = os.path.basename(path)
    if base not in _NATIVE_OBS_IMPL:
        for i, ln in enumerate(lines, 1):
            if "tpr_obs::emit" not in ln or _native_allowed(lines, i):
                continue
            out.append(LintViolation(
                path, i, ln.index("tpr_obs::emit"), "tpr-obs",
                "raw tpr_obs::emit() bypasses the TPR_OBS macro's "
                "enabled() guard: the off-switch must cost one relaxed "
                "load, not an emit — go through TPR_OBS; a deliberate "
                "exception carries '// tpr: allow(tpr-obs)'"))
    for m in re.finditer(r"\bTPR_OBS\s*\(", source):
        line = source.count("\n", 0, m.start()) + 1
        stripped = lines[line - 1].lstrip()
        if stripped.startswith("#define") or stripped.startswith("//"):
            continue
        if _native_allowed(lines, line):
            continue
        # balanced-paren argument extraction (sites span lines)
        depth, i = 1, m.end()
        while i < len(source) and depth:
            if source[i] == "(":
                depth += 1
            elif source[i] == ")":
                depth -= 1
            i += 1
        if depth:
            continue  # unbalanced tail: not a call site we can judge
        args = _native_split_args(source[m.end():i - 1])
        col = m.start() - (source.rfind("\n", 0, m.start()) + 1)

        def flag(msg: str) -> None:
            out.append(LintViolation(path, line, col, "tpr-obs", msg))

        if len(args) != 4:
            flag(f"TPR_OBS takes (code, tag, a1, a2); got {len(args)} "
                 "argument(s)")
            continue
        if not _NATIVE_CODE_RE.match(args[0]):
            flag(f"event code {args[0]!r} is not a static kEv* constant: "
                 "dynamic codes make the shared-ABI event vocabulary "
                 "unauditable (flight.py mirrors these numbers)")
        if "tag_for" in args[1]:
            flag("tag_for() in the tag argument interns per event: "
                 "intern ONCE at link/conn setup (the cold path) and "
                 "pass the cached uint16 — the flight rule's interned-"
                 "tag contract, on the C plane")
        for arg in args:
            if '"' in arg or "'" in arg:
                flag(f"argument {arg!r} carries a string/char literal: "
                     "events carry ints (tags are interned, names live "
                     "in the shm tag table)")
                break
        for arg in args[1:]:
            if "tag_for" in arg:
                continue  # already flagged with the specific story
            if _NATIVE_CALL_RE.search(arg):
                flag(f"argument {arg!r} calls a function per event: the "
                     "always-on C ring's writers pay 4 relaxed stores "
                     "and 2 seq stamps per record — precompute the int; "
                     "a deliberate exception carries "
                     "'// tpr: allow(tpr-obs)'")
                break
    out.sort(key=lambda v: (v.path, v.line, v.col))
    return out


def native_src_root() -> str:
    """The repo's ``native/src`` directory (sibling of the package)."""
    return os.path.join(os.path.dirname(tree_root()), "native", "src")


def lint_native_tree(root: Optional[str] = None) -> List[LintViolation]:
    """The ``tpr-obs`` pass over every C source under ``native/src``."""
    root = root or native_src_root()
    if not os.path.isdir(root):
        return []
    out: List[LintViolation] = []
    for fn in sorted(os.listdir(root)):
        if not fn.endswith((".cc", ".h", ".cpp", ".hpp")):
            continue
        p = os.path.join(root, fn)
        with open(p, "r", encoding="utf-8") as f:
            out.extend(lint_native_source(f.read(), p))
    return out


# -- driver ------------------------------------------------------------------

# -- rule: diag --------------------------------------------------------------

# Callee names that mutate a telemetry plane. Matched by name (Attribute
# attr or bare Name) because the evidence rules reach planes through the
# Planes facade and module handles — a cheap syntactic net that catches
# the real mutators (Counter.inc, flight.emit, watchdog.external_trip,
# tag_for interning, bundle capture) without a type system.
_DIAG_MUTATORS = frozenset({
    "inc", "dec", "set", "observe", "record", "emit", "capture",
    "external_trip", "tag_for", "sample_once", "reset", "clamp",
})
# Bare-name calls that are common builtins share names with mutators
# ("set" the constructor) — only these bare names count as mutation.
_DIAG_BARE_MUTATORS = frozenset({"emit", "tag_for", "external_trip"})


def _check_diag(tree: ast.AST, path: str,
                lines: Sequence[str]) -> List[LintViolation]:
    """Evidence rules (``_collect_*`` / ``_score_*``) must only READ the
    planes: a diagnosis that emits, bumps, trips or interns mutates the
    evidence the next diagnosis reads (the observer effect as a bug)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not (node.name.startswith("_collect_")
                or node.name.startswith("_score_")):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if isinstance(f, ast.Attribute):
                bad = f.attr in _DIAG_MUTATORS
            elif isinstance(f, ast.Name):
                bad = f.id in _DIAG_BARE_MUTATORS
            else:
                bad = False
            if not bad:
                continue
            if "diag" in _allowed_rules(lines, call.lineno):
                continue
            name = f.attr if isinstance(f, ast.Attribute) else f.id
            out.append(LintViolation(
                path, call.lineno, call.col_offset, "diag",
                f"{node.name} is an evidence rule and must be read-only, "
                f"but calls {name}(): mutating a telemetry plane from "
                "inside a diagnosis corrupts the evidence the next "
                "diagnosis reads — collect facts, return them; a "
                "deliberate exception carries '# tpr: allow(diag)'"))
    return out


def lint_source(source: str, path: str,
                hot_copy: Optional[bool] = None,
                hot_log: Optional[bool] = None,
                hot_flight: Optional[bool] = None) -> List[LintViolation]:
    """Lint one module's source. ``hot_copy``/``hot_log``/``hot_flight``
    force/suppress the no-copy, guarded-logging and flight-encoder rules
    (default: decided by ``path`` suffix against HOT_COPY_MODULES /
    HOT_LOG_MODULES / FLIGHT_HOT_MODULES)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [LintViolation(path, exc.lineno or 0, exc.offset or 0,
                              "syntax", str(exc))]
    _attach_parents(tree)
    lines = source.splitlines()
    out = []
    out.extend(_check_wallclock(tree, path, lines))
    if hot_copy is None:
        hot_copy = path.replace("\\", "/").endswith(
            tuple(m.replace(os.sep, "/") for m in HOT_COPY_MODULES))
    if hot_copy:
        out.extend(_check_copy(tree, path, lines))
    if hot_log is None:
        hot_log = path.replace("\\", "/").endswith(
            tuple(m.replace(os.sep, "/") for m in HOT_LOG_MODULES))
    if hot_log:
        out.extend(_check_log(tree, path, lines))
    if hot_flight is None:
        hot_flight = path.replace("\\", "/").endswith(
            tuple(m.replace(os.sep, "/") for m in FLIGHT_HOT_MODULES))
    if hot_flight:
        out.extend(_check_flight(tree, path, lines))
    norm = path.replace("\\", "/")
    for suffix, fns in INLINE_DISPATCH_PATH.items():
        if norm.endswith(suffix.replace(os.sep, "/")):
            out.extend(_check_block(tree, path, lines, frozenset(fns)))
    if norm.endswith("tpurpc/obs/diagnose.py"):
        out.extend(_check_diag(tree, path, lines))
    out.extend(_check_locks(tree, path, lines))
    out.extend(_check_shard(tree, path, lines))
    out.extend(_check_stage(tree, path, lines))
    out.extend(_check_lease(tree, path, lines))
    out.extend(_check_rdv(tree, path, lines))
    out.extend(_check_kv(tree, path, lines))
    out.extend(_check_ringpool(tree, path, lines))
    if norm.endswith(tuple(m.replace(os.sep, "/") for m in XPROC_MODULES)):
        out.extend(_check_xproc(tree, path, lines))
    out.extend(_check_rawlock(tree, path, lines))
    out.sort(key=lambda v: (v.path, v.line, v.col))
    return out


def lint_paths(paths: Iterable[str]) -> List[LintViolation]:
    out = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            out.extend(lint_source(f.read(), p))
    return out


def tree_root() -> str:
    """The repo's ``tpurpc`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree_paths(root: Optional[str] = None) -> List[str]:
    root = root or tree_root()
    paths = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    return paths


def lint_tree(root: Optional[str] = None) -> List[LintViolation]:
    """Lint every ``.py`` under the tpurpc package (the default CLI pass)."""
    return lint_paths(_tree_paths(root))
