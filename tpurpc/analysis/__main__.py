"""``python -m tpurpc.analysis`` — run the verification suite.

Default (no subcommand): AST lint over the whole ``tpurpc`` package + the
bounded exhaustive ring model check + the mutant kill check. Exit 0 iff all
pass — ``tools/check.sh`` and CI gate on this.

Subcommands::

    python -m tpurpc.analysis lint [paths...]   # lint only (default: tree)
    python -m tpurpc.analysis ringcheck [--capacity N] [--msgs 1,2,1]
                                        [--batched] [--mutant NAME]
    python -m tpurpc.analysis mutants           # mutant kill check only
    python -m tpurpc.analysis locks             # how to run the lock detector

The runtime lock-order detector is not a subcommand of its own — it is the
``TPURPC_DEBUG_LOCKS=1`` environment switch, exercised by running any
workload (the test suite, a bench) with it set; violations print to stderr
and are queryable via :func:`tpurpc.analysis.locks.lock_violations`.
"""

from __future__ import annotations

import argparse
import sys

from tpurpc.analysis import lint, ringcheck


def _run_lint(paths) -> int:
    violations = (lint.lint_paths(paths) if paths else lint.lint_tree())
    for v in violations:
        print(v)
    if violations:
        print(f"lint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("lint: clean")
    return 0


def _run_ringcheck(args) -> int:
    if args.capacity or args.msgs:
        cap = args.capacity or 4
        msgs = [int(t) for t in (args.msgs or "1,1,1").split(",")]
        res = ringcheck.check_ring(cap, msgs, batched=args.batched,
                                   mutant=args.mutant)
        print(repr(res))
        return 0 if res.ok else 1
    results = ringcheck.default_suite(verbose=True)
    bad = [r for r in results if not r.ok]
    total = sum(r.states for r in results)
    if bad:
        print(f"ringcheck: {len(bad)} violating config(s) "
              f"({total} states explored)", file=sys.stderr)
        return 1
    print(f"ringcheck: {len(results)} configs exhausted, {total} states, "
          "no violations")
    return 0


def _run_mutants() -> int:
    kills = ringcheck.mutant_kill_suite(verbose=True)
    survived = [m for m, killed in kills.items() if not killed]
    if survived:
        print(f"mutants: SURVIVORS {survived} — the checker lost its "
              "teeth", file=sys.stderr)
        return 1
    print(f"mutants: all {len(kills)} seeded protocol mutants killed")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpurpc.analysis",
                                 description=__doc__.split("\n\n")[0])
    sub = ap.add_subparsers(dest="cmd")
    p_lint = sub.add_parser("lint", help="AST lint (lease/copy/lock/clock)")
    p_lint.add_argument("paths", nargs="*")
    p_ring = sub.add_parser("ringcheck", help="SPSC ring model checker")
    p_ring.add_argument("--capacity", type=int, default=0)
    p_ring.add_argument("--msgs", default="")
    p_ring.add_argument("--batched", action="store_true")
    p_ring.add_argument("--mutant", default=None,
                        choices=list(ringcheck.MUTANTS))
    sub.add_parser("mutants", help="verify seeded mutants are caught")
    sub.add_parser("locks", help="runtime lock-order detector usage")
    args = ap.parse_args(argv)

    if args.cmd == "lint":
        return _run_lint(args.paths)
    if args.cmd == "ringcheck":
        return _run_ringcheck(args)
    if args.cmd == "mutants":
        return _run_mutants()
    if args.cmd == "locks":
        print("Runtime lock-order detection is environment-driven:\n"
              "  TPURPC_DEBUG_LOCKS=1 python -m pytest tests/ -q\n"
              "Cycles in the lock acquisition graph, cv-waits holding other "
              "locks,\nand locks held across instrumented blocking calls "
              "print to stderr;\ntpurpc.analysis.locks.lock_violations() "
              "returns them programmatically.")
        return 0

    # default: the full static gate
    rc = _run_lint(None)
    rc |= _run_ringcheck(argparse.Namespace(capacity=0, msgs="",
                                            batched=False, mutant=None))
    rc |= _run_mutants()
    return rc


if __name__ == "__main__":
    sys.exit(main())
