"""``python -m tpurpc.analysis`` — run the verification suite.

Default (no subcommand): AST lint (+ the stale-suppression audit) over
the whole ``tpurpc`` package + the bounded exhaustive ring model check +
the mutant kill check + the protocol-machine self-test (good trace
accepted, seeded event-order mutants killed) + the quick deterministic
schedule exploration (clean scenarios exhausted at bound 1, seeded
real-code mutants killed) + the quick distributed simulation (simnet:
cross-process scenarios exhausted, seeded distributed mutants killed).
Exit 0 iff all pass — ``tools/check.sh`` and CI gate on this.

Subcommands::

    python -m tpurpc.analysis lint [paths...]   # lint only (default: tree)
    python -m tpurpc.analysis ringcheck [--capacity N] [--msgs 1,2,1]
                                        [--batched] [--mutant NAME]
    python -m tpurpc.analysis mutants           # ring mutant kill check
    python -m tpurpc.analysis schedule [--quick] [--scenario NAME]
                                       [--bound K] [--mutant NAME]
                                       [--max-schedules N]
    python -m tpurpc.analysis simnet [--quick] [--scenario NAME]
                                     [--bound K] [--mutant NAME]
                                     [--max-schedules N]
    python -m tpurpc.analysis protocol [--flight DUMP]... [--strict]
    python -m tpurpc.analysis locks             # how to run the lock detector

``--flight DUMP`` (a ``flight.snapshot()`` JSON file, a ``/debug/flight``
body, or a ``TPURPC_FLIGHT_DUMP`` directory of them) is also accepted at
the top level as shorthand for ``protocol --flight DUMP``.

The runtime lock-order detector is not a subcommand of its own — it is the
``TPURPC_DEBUG_LOCKS=1`` environment switch, exercised by running any
workload (the test suite, a bench) with it set; violations print to stderr
and are queryable via :func:`tpurpc.analysis.locks.lock_violations`. The
live protocol verifier is its sibling switch: ``TPURPC_VERIFY_PROTOCOL=1``
checks every flight event against the declared machines as it is recorded.
"""

from __future__ import annotations

import argparse
import sys

from tpurpc.analysis import lint, ringcheck


def _run_lint(paths) -> int:
    violations = (lint.lint_paths(paths) if paths else lint.lint_tree())
    violations = violations + (lint.audit_suppressions(paths) if paths
                               else lint.audit_suppressions_tree())
    if not paths:
        # tpurpc-xray (ISSUE 19): the C plane's emission sites ride the
        # same gate — TPR_OBS discipline over native/src
        violations = violations + lint.lint_native_tree()
    for v in violations:
        print(v)
    if violations:
        print(f"lint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("lint: clean (incl. suppression audit)")
    return 0


def _run_ringcheck(args) -> int:
    if args.capacity or args.msgs:
        cap = args.capacity or 4
        msgs = [int(t) for t in (args.msgs or "1,1,1").split(",")]
        res = ringcheck.check_ring(cap, msgs, batched=args.batched,
                                   mutant=args.mutant)
        print(repr(res))
        return 0 if res.ok else 1
    results = ringcheck.default_suite(verbose=True)
    bad = [r for r in results if not r.ok]
    total = sum(r.states for r in results)
    if bad:
        print(f"ringcheck: {len(bad)} violating config(s) "
              f"({total} states explored)", file=sys.stderr)
        return 1
    print(f"ringcheck: {len(results)} configs exhausted, {total} states, "
          "no violations")
    return 0


def _run_mutants() -> int:
    kills = ringcheck.mutant_kill_suite(verbose=True)
    survived = [m for m, killed in kills.items() if not killed]
    if survived:
        print(f"mutants: SURVIVORS {survived} — the checker lost its "
              "teeth", file=sys.stderr)
        return 1
    print(f"mutants: all {len(kills)} seeded protocol mutants killed")
    return 0


def _run_schedule(args) -> int:
    from tpurpc.analysis import schedule

    if args.scenario:
        res = schedule.run_scenario(
            args.scenario, preemption_bound=args.bound,
            max_schedules=args.max_schedules, mutant=args.mutant)
        print(repr(res))
        if args.mutant:
            killed = res.violation is not None
            print(f"schedule: mutant {args.mutant}: "
                  f"{'KILLED' if killed else 'SURVIVED'}")
            return 0 if killed else 1
        return 0 if res.ok else 1
    results = schedule.quick_suite(verbose=True)
    bad = [r for r in results if not r.ok]
    total = sum(r.schedules for r in results)
    if bad:
        print(f"schedule: {len(bad)} failing entr(ies) of {len(results)} "
              f"({total} schedules)", file=sys.stderr)
        return 1
    print(f"schedule: {len(results)} entries clean, {total} schedules "
          "explored (quick suite, bound 1)")
    return 0


def _run_simnet(args) -> int:
    from tpurpc.analysis import simnet

    if args.scenario:
        res = simnet.run_scenario(
            args.scenario, preemption_bound=args.bound,
            max_schedules=args.max_schedules, mutant=args.mutant)
        print(repr(res))
        if args.mutant:
            killed = res.violation is not None
            print(f"simnet: mutant {args.mutant}: "
                  f"{'KILLED' if killed else 'SURVIVED'}")
            return 0 if killed else 1
        return 0 if res.ok else 1
    results = simnet.quick_suite(verbose=True)
    bad = [r for r in results if not r.ok]
    total = sum(r.schedules for r in results)
    if bad:
        print(f"simnet: {len(bad)} failing entr(ies) of {len(results)} "
              f"({total} schedules)", file=sys.stderr)
        return 1
    print(f"simnet: {len(results)} entries clean, {total} schedules "
          "explored (quick suite: scenarios bound 1, mutants bound 2)")
    return 0


def _run_protocol(flight_path, strict: bool) -> int:
    from tpurpc.analysis import protocol

    if flight_path:
        paths = ([flight_path] if isinstance(flight_path, str)
                 else list(flight_path))
        label = ", ".join(paths)
        try:
            total, violations = protocol.check_dumps(paths, strict=strict)
        except (OSError, ValueError) as exc:
            print(f"protocol: cannot read {label}: {exc}",
                  file=sys.stderr)
            return 1
        for v in violations:
            print(v)
        if violations:
            print(f"protocol: {len(violations)} violation(s) over "
                  f"{total} events in {label}", file=sys.stderr)
            return 1
        merged = " + merged cross-process pairing" if len(paths) > 1 else ""
        print(f"protocol: {total} events conform "
              f"({len(protocol.MACHINES)} machines, "
              f"{'strict' if strict else 'tolerant'}{merged})")
        return 0
    failures = protocol.self_test(verbose=True)
    for f in failures:
        print(f, file=sys.stderr)
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpurpc.analysis",
                                 description=__doc__.split("\n\n")[0])
    ap.add_argument("--flight", default=None, metavar="DUMP",
                    help="shorthand for: protocol --flight DUMP")
    ap.add_argument("--strict", action="store_true",
                    help="with --flight: treat mid-history events as "
                         "violations (fresh-recorder dumps only)")
    sub = ap.add_subparsers(dest="cmd")
    p_lint = sub.add_parser("lint", help="AST lint (lease/copy/lock/clock)")
    p_lint.add_argument("paths", nargs="*")
    p_ring = sub.add_parser("ringcheck", help="SPSC ring model checker")
    p_ring.add_argument("--capacity", type=int, default=0)
    p_ring.add_argument("--msgs", default="")
    p_ring.add_argument("--batched", action="store_true")
    p_ring.add_argument("--mutant", default=None,
                        choices=list(ringcheck.MUTANTS))
    sub.add_parser("mutants", help="verify seeded ring mutants are caught")
    p_sched = sub.add_parser(
        "schedule", help="deterministic schedule exploration (live code)")
    p_sched.add_argument("--quick", action="store_true",
                         help="bounded quick suite (the default)")
    p_sched.add_argument("--scenario", default=None,
                         help="explore one scenario by name")
    p_sched.add_argument("--bound", type=int, default=2,
                         help="preemption bound (with --scenario)")
    p_sched.add_argument("--max-schedules", type=int, default=20000)
    p_sched.add_argument("--mutant", default=None,
                         help="apply a seeded real-code mutant")
    p_sim = sub.add_parser(
        "simnet", help="deterministic distributed simulation (live code)")
    p_sim.add_argument("--quick", action="store_true",
                       help="bounded quick suite (the default)")
    p_sim.add_argument("--scenario", default=None,
                       help="explore one simnet scenario by name")
    p_sim.add_argument("--bound", type=int, default=2,
                       help="preemption bound (with --scenario)")
    p_sim.add_argument("--max-schedules", type=int, default=20000)
    p_sim.add_argument("--mutant", default=None,
                       help="apply a seeded distributed mutant")
    p_proto = sub.add_parser(
        "protocol", help="flight-event protocol conformance")
    p_proto.add_argument("--flight", action="append", default=None,
                         metavar="DUMP",
                         help="check a flight dump file or dump directory; "
                              "repeat for per-process dumps of ONE run — "
                              "anchored dumps are rebased onto the shared "
                              "wall clock and checked as a MERGED stream "
                              "(default: machine self-test)")
    p_proto.add_argument("--strict", action="store_true")
    sub.add_parser("locks", help="runtime lock-order detector usage")
    args = ap.parse_args(argv)

    if args.cmd == "lint":
        return _run_lint(args.paths)
    if args.cmd == "ringcheck":
        return _run_ringcheck(args)
    if args.cmd == "mutants":
        return _run_mutants()
    if args.cmd == "schedule":
        return _run_schedule(args)
    if args.cmd == "simnet":
        return _run_simnet(args)
    if args.cmd == "protocol":
        return _run_protocol(args.flight, args.strict)
    if args.cmd == "locks":
        print("Runtime lock-order detection is environment-driven:\n"
              "  TPURPC_DEBUG_LOCKS=1 python -m pytest tests/ -q\n"
              "Cycles in the lock acquisition graph, cv-waits holding other "
              "locks,\nand locks held across instrumented blocking calls "
              "print to stderr;\ntpurpc.analysis.locks.lock_violations() "
              "returns them programmatically.\n\n"
              "Live protocol conformance is its sibling:\n"
              "  TPURPC_VERIFY_PROTOCOL=1 <any workload>\n"
              "checks every flight event against the declared machines as "
              "it is\nrecorded; a violation emits a proto-violation flight "
              "event and trips\nthe stall watchdog (stage `protocol`).")
        return 0
    if args.flight:
        return _run_protocol(args.flight, args.strict)

    # default: the full static gate — lint + ring models + ring mutants +
    # protocol machines + quick schedule exploration
    rc = _run_lint(None)
    rc |= _run_ringcheck(argparse.Namespace(capacity=0, msgs="",
                                            batched=False, mutant=None))
    rc |= _run_mutants()
    rc |= _run_protocol(None, False)
    rc |= _run_schedule(argparse.Namespace(quick=True, scenario=None,
                                           bound=1, max_schedules=1500,
                                           mutant=None))
    rc |= _run_simnet(argparse.Namespace(quick=True, scenario=None,
                                         bound=1, max_schedules=200,
                                         mutant=None))
    return rc


if __name__ == "__main__":
    sys.exit(main())
