"""tpurpc-simnet: deterministic distributed simulation of the live
cross-process protocols (ISSUE 17).

:mod:`tpurpc.analysis.schedule` proves the THREADED half of "runtime
matches model": the real classes, explored under a cooperative scheduler.
This module is the DISTRIBUTED half. The cross-process protocols — the
KV handoff (OfferKv -> one-sided writes -> CompleteKv), migration,
ctrl-ring park/kick, scheduler adoption vs drain — are exercised by the
REAL classes (:class:`~tpurpc.serving.disagg.DisaggDecode`,
``_KvShipper``/``migrate``, :class:`~tpurpc.core.ctrlring.CtrlPlane`,
:class:`~tpurpc.serving.scheduler.DecodeScheduler`) running as N
*simulated nodes* inside one explored process.

The transport seam
------------------

:mod:`tpurpc.core.transport` is the one door every cross-process effect
walks through (the analogue of PR 12's lock-factory seam): framed sends
(``"frame"``), ring posts (``"post"``), one-sided window landings
(``"write"``) and doorbell kicks (``"kick"``) all go via
``transport.dispatch(point, obj, fn, *args)``. In production the hook is
``None`` and dispatch is a single None-check. Under simnet the hook
routes each effect onto a per-direction FIFO *link* whose delivery is a
courier task — so every message's delivery becomes a scheduler pick that
the DFS / preemption-bounded explorer in :mod:`schedule` enumerates:

* **delivery order** — couriers are ordinary tasks; the explorer decides
  when each queued effect lands relative to every other task step;
* **ordering contract** — effects on the SAME directed link deliver
  FIFO (the RDMA same-QP rule: a one-sided write issued before a send is
  visible before it). Cross-link orders are unconstrained;
* **bounded delay** — a courier left unscheduled models arbitrary but
  finite delay; untimed parks that can never be woken surface as the
  explorer's deadlock violation (reported, never hung);
* **partitions** — a partitioned link holds its entries; ``heal``
  releases them (shared-memory stores — the ``"post"`` point — land
  immediately: partitioning models the framed/TCP plane);
* **crash-at-any-point** — ``crash_after(node, k)`` kills the node at
  its (k+1)-th transport interaction; already-queued effects FROM the
  dead node still deliver (the straggling-NIC rule the quarantine
  protocol exists for), effects TO it are dropped.

Invariants are DECLARED per scenario (``net.invariant(fn)``) and checked
by couriers at every quiescent point (after each delivered effect), plus
a final ``check`` after all drivers retire: arena accounting conserved,
no sequence lost or duplicated across a migration, stale one-sided
writes land only in quarantined/never-re-leased memory, drain refuses at
the gate or finishes what it accepted. Liveness is the explorer's
deadlock rule plus per-scenario outcome attribution: every submitted
operation must retire or fail with a recorded reason; a quiescent
non-final state raises a :class:`SchedViolation` naming what hung, with
the replayable pick trace.

Seeded distributed mutants (a COMPLETE sent before the write, a reap
that frees instead of quarantining, a drain that drops resumable
sequences, a skipped ring kick, the pre-fix close/complete race) live in
:mod:`tpurpc.analysis.simmutants`; the kill suite proves each dies at
small bounds.

CLI: ``python -m tpurpc.analysis simnet [--quick]`` — the quick suite
rides the default analysis gate and ``tools/check.sh`` (``simnet-quick``).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tpurpc.analysis.schedule import (ExploreResult, Scenario, SchedEvent,
                                      SchedViolation, Violation, _Scheduler,
                                      _module_file, explore, explore_random,
                                      replay)
from tpurpc.core import transport as _transport

__all__ = [
    "NodeCrashed", "SimRpcError", "SimNet", "SimChannel",
    "SIM_SCENARIOS", "run_scenario", "quick_suite", "mutant_kill_suite",
]


class NodeCrashed(Exception):
    """Raised at a dead node's next transport interaction — the simulated
    process is gone; its driver unwinds (``on_node`` absorbs it)."""


class SimRpcError(RuntimeError):
    """A simulated RPC failure: what ``ctx.abort`` raises on the handler
    side and the caller re-raises — carries the grpc-shaped status."""

    def __init__(self, code, details: str):
        super().__init__(f"{code}: {details}")
        self.code = code
        self.details = details


class _SimContext:
    """The handler-facing slice of a server RPC context."""

    def is_active(self) -> bool:
        return True

    def abort(self, code, details: str):
        raise SimRpcError(code, details)

    def set_trailing_metadata(self, md) -> None:
        pass

    def invocation_metadata(self):
        return []


class _Link:
    """One directed link: a FIFO of (deliver, label) effects plus the
    courier's wake event and the partition flag."""

    __slots__ = ("src", "dst", "entries", "evt", "partitioned", "dropped")

    def __init__(self, src: str, dst: str, sched: _Scheduler):
        self.src = src
        self.dst = dst
        self.entries: "deque[Tuple[Callable[[], None], str]]" = deque()
        self.evt = SchedEvent(sched, f"simnet:{src}->{dst}")
        self.partitioned = False
        self.dropped: List[str] = []


class SimNet:
    """The simulated network: named nodes, directed FIFO links, and the
    transport hook that turns every cross-node effect into a courier
    delivery the explorer schedules. Built in a scenario's ``setup``;
    ``install()`` arms the hook, ``close()`` (teardown) disarms it."""

    def __init__(self, sched: _Scheduler, nodes: List[str]):
        self._sched = sched
        self.nodes = list(nodes)
        self.alive: Dict[str, bool] = {n: True for n in nodes}
        self.links: Dict[Tuple[str, str], _Link] = {
            (a, b): _Link(a, b, sched)
            for a in nodes for b in nodes if a != b}
        self._tls = threading.local()
        #: routed objects: id -> (obj, dst); the obj ref pins the id
        self._routes: Dict[int, Tuple[Any, str]] = {}
        self._default_dst: Dict[str, str] = {}
        self._invariants: List[Callable[[], None]] = []
        self._sent: Dict[str, int] = {n: 0 for n in nodes}
        self._crash_at: Dict[str, int] = {}
        self.delivered: List[str] = []
        self.handler_faults: List[str] = []
        self.drivers_expected = 0
        self.drivers_done = 0

    # -- wiring ---------------------------------------------------------------

    def route(self, obj: Any, dst: str) -> None:
        """Effects dispatched on ``obj`` deliver to ``dst``."""
        self._routes[id(obj)] = (obj, dst)

    def default_route(self, src: str, dst: str) -> None:
        """Unrouted effects dispatched while ``src``'s code runs deliver
        to ``dst`` (the single-peer case: a shipper's GrantWriter is born
        inside ``migrate``, so per-object routing can't see it)."""
        self._default_dst[src] = dst

    def invariant(self, fn: Callable[[], None]) -> None:
        """Checked at every quiescent point (after each delivery); raise
        :class:`SchedViolation` to report."""
        self._invariants.append(fn)

    def install(self) -> None:
        _transport.set_transport_hook(self._hook)

    def close(self) -> None:
        if _transport.transport_hook() is self._hook:
            _transport.set_transport_hook(None)

    # -- node context ---------------------------------------------------------

    @contextlib.contextmanager
    def on(self, node: str):
        prev = getattr(self._tls, "node", None)
        self._tls.node = node
        try:
            yield
        finally:
            self._tls.node = prev

    def current_node(self) -> Optional[str]:
        return getattr(self._tls, "node", None)

    def on_node(self, node: str, fn: Callable[[dict], None]
                ) -> Callable[[dict], None]:
        """Wrap a driver body to run in ``node``'s context; a crash ends
        the driver cleanly (the process died — that IS the behavior)."""
        def body(state: dict) -> None:
            try:
                with self.on(node):
                    fn(state)
            except NodeCrashed:
                pass
            finally:
                self.drivers_done += 1
                if self.drivers_done >= self.drivers_expected:
                    self._broadcast()
        return body

    # -- failure injection ----------------------------------------------------

    def crash_after(self, node: str, interactions: int) -> None:
        """Kill ``node`` at its (interactions+1)-th transport interaction:
        queued effects FROM it still deliver (straggler writes), effects
        TO it drop, its drivers unwind via :class:`NodeCrashed`."""
        self._crash_at[node] = int(interactions)

    def kill(self, node: str) -> None:
        self.alive[node] = False
        self._broadcast()

    def partition(self, a: str, b: str) -> None:
        """Cut the framed plane both ways; queued + new effects are HELD
        (not lost) until :meth:`heal`."""
        self.links[(a, b)].partitioned = True
        self.links[(b, a)].partitioned = True

    def heal(self, a: str, b: str) -> None:
        for key in ((a, b), (b, a)):
            link = self.links[key]
            link.partitioned = False
            link.evt.set()

    # -- the transport hook ---------------------------------------------------

    def _tick(self, node: str) -> None:
        if not self.alive[node]:
            raise NodeCrashed(node)
        self._sent[node] += 1
        k = self._crash_at.get(node)
        if k is not None and self._sent[node] > k:
            self.kill(node)
            raise NodeCrashed(node)

    def _hook(self, point: str, obj: Any, fn: Callable, args, kwargs):
        node = self.current_node()
        if node is None:
            return NotImplemented  # not simulated code: pass through
        if point == "post":
            # a ring post is a shared-memory store: it lands immediately
            # (partitions model the framed plane) but still counts as an
            # interaction for crash sweeps
            self._tick(node)
            return NotImplemented
        ent = self._routes.get(id(obj))
        dst = ent[1] if ent is not None else self._default_dst.get(node)
        if dst is None or dst == node:
            return NotImplemented
        self.post(node, dst, f"{point}:{type(obj).__name__}",
                  lambda: fn(*args, **kwargs))
        return True  # claimed: a "frame" dispatch must read as sent

    def post(self, src: str, dst: str, label: str,
             fn: Callable[[], None]) -> None:
        """Enqueue one effect on the ``src -> dst`` link (counts as an
        interaction at ``src``). The courier runs ``fn`` in ``dst``'s
        node context, so nested sends route from the receiver."""
        self._tick(src)
        link = self.links[(src, dst)]

        def deliver() -> None:
            with self.on(dst):
                fn()

        link.entries.append((deliver, label))
        link.evt.set()

    # -- couriers -------------------------------------------------------------

    def courier(self, src: str, dst: str) -> Callable[[dict], None]:
        """The delivery task for one directed link (add to a scenario's
        ``threads``). Runs queued effects in order, checks the declared
        invariants after each, exits when every driver finished and all
        queues drained."""
        def body(state: dict) -> None:
            self._courier(src, dst)
        return body

    def _courier(self, src: str, dst: str) -> None:
        link = self.links[(src, dst)]
        while True:
            link.evt.clear()
            while link.entries and not link.partitioned:
                deliver, label = link.entries.popleft()
                if not self.alive[dst]:
                    link.dropped.append(label)
                    continue
                try:
                    deliver()
                except NodeCrashed:
                    pass  # the receiver died mid-handler: effect lost
                self.delivered.append(f"{src}->{dst} {label}")
                self._check_invariants()
            if self._quiesced():
                # flush a permanently-partitioned backlog into dropped so
                # the final check can attribute the loss
                while link.entries:
                    link.dropped.append(link.entries.popleft()[1])
                self._broadcast()
                return
            link.evt.wait()  # untimed: a lost wakeup IS a deadlock report

    def _quiesced(self) -> bool:
        if self.drivers_done < self.drivers_expected:
            return False
        return all((not l.entries) or l.partitioned
                   for l in self.links.values())

    def _broadcast(self) -> None:
        for link in self.links.values():
            link.evt.set()

    def _check_invariants(self) -> None:
        for fn in self._invariants:
            fn()

    # -- driver utilities -----------------------------------------------------

    def settle(self) -> None:
        """A deterministic yield for driver polling loops: park timed; the
        explorer wakes us only when nothing else can run."""
        SchedEvent(self._sched, "simnet.settle").wait(timeout=0.001)

    def assert_delivered(self) -> None:
        """Final-check helper: nothing still queued or silently dropped."""
        stuck = [f"{l.src}->{l.dst}:{len(l.entries)} queued"
                 for l in self.links.values() if l.entries]
        if stuck:
            raise SchedViolation(
                f"simnet quiesced with undelivered effects: {stuck}")


class _SimMethod:
    """One unary-unary RPC face: the request rides the src->dst link, the
    handler runs at the receiver, the response rides dst->src; the caller
    parks (timed) until it lands or a peer dies."""

    def __init__(self, net: SimNet, src: str, dst: str, method: str,
                 handler: Callable):
        self._net = net
        self._src = src
        self._dst = dst
        self._method = method
        self._handler = handler

    def __call__(self, request, timeout: Optional[float] = None):
        net, src, dst = self._net, self._src, self._dst
        box: List[Any] = []
        evt = SchedEvent(net._sched, f"rpc:{self._method}")

        def respond(result) -> None:
            box.append(result)
            evt.set()

        def handle() -> None:
            ctx = _SimContext()
            try:
                resp = self._handler(request, ctx)
            except SimRpcError as exc:
                resp = exc
            except NodeCrashed:
                raise
            except Exception as exc:  # a handler bug: surfaced, not hung
                net.handler_faults.append(
                    f"{self._method}: {type(exc).__name__}: {exc}")
                resp = SimRpcError("INTERNAL", repr(exc))
            net.post(dst, src, f"resp:{self._method}",
                     lambda: respond(resp))

        net.post(src, dst, f"req:{self._method}", handle)
        for _ in range(20000):
            if box:
                break
            if not net.alive[dst]:
                raise OSError(f"simnet: peer {dst} is dead")
            if not net.alive[src]:
                raise NodeCrashed(src)
            evt.wait(timeout=0.001)
        else:
            raise RuntimeError(f"simnet rpc {self._method} never settled")
        result = box[0]
        if isinstance(result, SimRpcError):
            raise result
        return result

    def pipeline(self, depth: int = 1):
        raise NotImplementedError(
            "simnet channels model single-call RPCs; bursts of one ride "
            "the fast path")


class SimChannel:
    """The client-channel face a :class:`_KvShipper` needs, bound to one
    simulated direction: ``unary_unary`` hands back a :class:`_SimMethod`
    whose request/response legs are courier deliveries."""

    def __init__(self, net: SimNet, src: str, dst: str,
                 handlers: Dict[str, Callable]):
        self._net = net
        self._src = src
        self._dst = dst
        self._handlers = dict(handlers)

    def unary_unary(self, method: str, serializer, deserializer
                    ) -> _SimMethod:
        try:
            handler = self._handlers[method]
        except KeyError:
            raise KeyError(f"simnet channel has no handler for {method}")
        # codecs are identity in-sim: the real tree codec is exercised by
        # the RPC-plane tests; simnet explores ORDERING, not encoding
        return _SimMethod(self._net, self._src, self._dst, method, handler)

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Shared scenario plumbing.
# ---------------------------------------------------------------------------

class _StubSched:
    """The scheduler face DisaggDecode needs when a scenario exercises
    only the KV-handoff plane."""

    def __init__(self, name: str = "simnet"):
        self.name = name

    def state_str(self) -> str:
        return "ok"


def _ship_payload(n_tokens: int = 4):
    """A real KV image to ship: ``(prompt, payload bytes, entries)`` built
    through a throwaway arena so the bytes have the genuine entry layout
    (nonzero hashes — the 'bytes actually landed' invariant's signal)."""
    from tpurpc.serving import kv as _kv

    m = _kv.KvBlockManager(n_blocks=2, block_bytes=_kv.ENTRY_BYTES * 2,
                           kind="local", name="simnet-src")
    try:
        prompt = np.arange(1, n_tokens + 1, dtype=np.int32)
        skv, _hit = m.alloc_for_prompt(99, prompt)  # tpr: allow(kv)
        for i in range(n_tokens):
            skv.append(0x5A5A0 + i + 1, int(prompt[i]))
        payload = b"".join(bytes(v) for _b, v in skv.chunks(0, n_tokens))
        entries = [skv.entry(i) for i in range(n_tokens)]
        m.free_blocks(skv)
    finally:
        m.close()
    return prompt, payload, entries


def _cache_blocks(mgr) -> set:
    return {b for ent in mgr._prefix.values() for b in ent.blocks}


def _accounted(mgr, owners=()) -> None:
    """The conservation invariant: every arena block is free, quarantined,
    prefix-cached, or owned by a named live table — a block in none of
    those is leaked forever."""
    owned = set()
    for kv in owners:
        if kv is not None:
            owned |= set(kv.blocks)
    have = (set(mgr._free) | set(mgr._quarantined) | _cache_blocks(mgr)
            | owned)
    missing = set(range(mgr.n_blocks)) - have
    if missing:
        raise SchedViolation(
            f"arena accounting violated: blocks {sorted(missing)} are "
            "neither free, quarantined, cached, nor owned by any live "
            "table — leaked (zero-leak close/reap contract)")


def _mutants_file() -> str:
    from tpurpc.analysis import simmutants

    return simmutants.__file__


# ---------------------------------------------------------------------------
# Scenario 1: the clean KV handoff, offer -> one-sided write -> complete.
# ---------------------------------------------------------------------------

def _kvship_scenario() -> Scenario:
    """Prefill node P ships one sequence's KV to decode node D through the
    real ``_KvShipper`` + ``DisaggDecode`` handlers over a simulated
    link. Declared invariant (checked at every quiescent point): a PARKED
    sequence's bytes have landed — COMPLETE processed before the
    one-sided write delivers is the ordering bug the FIFO link contract
    (and the real RDMA QP) forbids, and what the
    ``ship_complete_before_write`` mutant reintroduces."""
    from tpurpc.serving import disagg as _disagg
    from tpurpc.serving import kv as _kv

    def setup(sched: _Scheduler):
        net = SimNet(sched, ["P", "D"])
        prompt, payload, entries = _ship_payload(4)
        mgr = _kv.KvBlockManager(n_blocks=8,
                                 block_bytes=_kv.ENTRY_BYTES * 2,
                                 kind="local", name="simnet-kvship")
        decode = _disagg.DisaggDecode(_StubSched("sim-kvship"), mgr)
        chan = SimChannel(net, "P", "D", {
            _disagg._method("OfferKv"): decode.on_offer,
            _disagg._method("CompleteKv"): decode.on_complete,
            _disagg._method("ReleaseKv"): decode.on_release,
        })
        shipper = _disagg._KvShipper(chan)
        net.default_route("P", "D")

        def parked_bytes_landed() -> None:
            for key, parked in list(decode._parked.items()):
                n = parked.kv.length
                if n and parked.kv.entry(n - 1)[0] == 0:
                    raise SchedViolation(
                        f"sequence {key} PARKED before its bytes landed "
                        "(zero entry hash at the tail): COMPLETE was "
                        "processed ahead of the one-sided write — the "
                        "write-before-complete ordering contract is "
                        "broken")
        net.invariant(parked_bytes_landed)
        net.install()
        return {"net": net, "mgr": mgr, "decode": decode,
                "shipper": shipper, "prompt": prompt, "payload": payload,
                "entries": entries, "shipped": [], "err": []}

    def sender(state):
        sh = state["shipper"]
        try:
            grant, handoff, pos, _rh, _rf = sh.offer(
                501, state["prompt"], 4, timeout=5.0)
            sh.ship(grant, handoff, memoryview(state["payload"]), 4,
                    last_token=int(state["prompt"][-1]), emitted=1,
                    timeout=5.0)
            state["shipped"].append(handoff)
        except Exception as exc:
            state["err"].append(repr(exc))

    def check(state):
        net, decode, mgr = state["net"], state["decode"], state["mgr"]
        net.assert_delivered()
        if net.handler_faults:
            raise SchedViolation(
                f"handler faults: {net.handler_faults}")
        if state["err"] or not state["shipped"]:
            raise SchedViolation(
                "clean handoff did not complete: "
                f"err={state['err']} shipped={state['shipped']} — every "
                "submitted ship must retire or fail with attribution")
        parked = decode._parked.get(501)
        if parked is None:
            raise SchedViolation(
                "sequence lost: COMPLETE succeeded at the sender but 501 "
                "is not parked at the receiver")
        got = [parked.kv.entry(i) for i in range(parked.kv.length)]
        if got != state["entries"]:
            raise SchedViolation(
                f"shipped KV content diverged: {got} != "
                f"{state['entries']}")
        if decode._pending:
            raise SchedViolation(
                f"pending registry not drained: {list(decode._pending)}")
        _accounted(mgr, owners=[p.kv for p in decode._parked.values()])

    def teardown(state):
        state["net"].close()
        try:
            state["decode"].close()
            state["mgr"].close()
        except Exception:
            pass

    return _with_couriers(
        "simnet-kvship", setup, [("P", sender)], check,
        [_module_file(_disagg), _mutants_file()], teardown, ["P", "D"])


def _with_couriers(scenario_name: str, setup, drivers, check, instrument,
                   teardown, nodes: List[str],
                   max_steps: int = 120000) -> Scenario:
    """Assemble a Scenario whose threads are the node drivers plus one
    courier per directed link. ``drivers`` is ``[(node, fn), ...]``."""
    def full_setup(sched: _Scheduler):
        state = setup(sched)
        state["net"].drivers_expected = len(drivers)
        return state

    threads: List[Callable] = []

    def make_driver(node, fn):
        def body(state):
            state["net"].on_node(node, fn)(state)
        return body

    for node, fn in drivers:
        threads.append(make_driver(node, fn))
    for a in nodes:
        for b in nodes:
            if a != b:
                def make_courier(src=a, dst=b):
                    def body(state):
                        state["net"]._courier(src, dst)
                    return body
                threads.append(make_courier())
    return Scenario(scenario_name, full_setup, threads, check,
                    instrument=instrument, teardown=teardown,
                    max_steps=max_steps)


# ---------------------------------------------------------------------------
# Scenario 2: sender dies between its one-sided write and COMPLETE — the
# straggling-writer case the TTL reap + quarantine protocol exists for.
# ---------------------------------------------------------------------------

def _kvship_death_scenario() -> Scenario:
    """P crashes at its third transport interaction: OFFER lands, the
    one-sided write is queued (an RDMA NIC can have it in flight long
    after the process died), COMPLETE never sends. The receiver's TTL
    reap fires. Declared invariants: the reap QUARANTINES the claimed
    blocks (never frees them back to the lease pool — the
    ``reap_free_instead_of_quarantine`` mutant's discipline), and a
    probe that then leases everything it can must never see its memory
    corrupted by the straggler."""
    from tpurpc.serving import disagg as _disagg
    from tpurpc.serving import kv as _kv

    _SENT = b"PROBE-OK"

    def setup(sched: _Scheduler):
        net = SimNet(sched, ["P", "D"])
        prompt, payload, entries = _ship_payload(4)
        mgr = _kv.KvBlockManager(n_blocks=8,
                                 block_bytes=_kv.ENTRY_BYTES * 2,
                                 kind="local", name="simnet-death")
        decode = _disagg.DisaggDecode(_StubSched("sim-death"), mgr,
                                      pending_ttl_s=30.0)
        chan = SimChannel(net, "P", "D", {
            _disagg._method("OfferKv"): decode.on_offer,
            _disagg._method("CompleteKv"): decode.on_complete,
            _disagg._method("ReleaseKv"): decode.on_release,
        })
        shipper = _disagg._KvShipper(chan)
        net.default_route("P", "D")
        # interactions at P: OFFER request (1), the one-sided write (2),
        # COMPLETE request (3) -> dies issuing COMPLETE, write in flight
        net.crash_after("P", 2)
        net.install()
        return {"net": net, "mgr": mgr, "decode": decode,
                "shipper": shipper, "prompt": prompt, "payload": payload,
                "probe": [], "probe_blocks": [], "reap": []}

    def sender(state):
        sh = state["shipper"]
        grant, handoff, _pos, _rh, _rf = sh.offer(
            502, state["prompt"], 4, timeout=5.0)
        sh.ship(grant, handoff, memoryview(state["payload"]), 4,
                last_token=4, emitted=1, timeout=5.0)

    def receiver(state):
        net, decode, mgr = state["net"], state["decode"], state["mgr"]
        for _ in range(300):
            if decode.stats()["pending"]:
                break
            net.settle()
        else:
            state["reap"].append("offer-never-arrived")
            return
        nq, nfreed = decode.reap(now=time.monotonic() + 1e6)
        state["reap"].append((nq, nfreed))
        state["q_after_reap"] = mgr.quarantined_count()
        # the adversarial probe: lease EVERYTHING the arena will give and
        # stamp it — if the dead sender's write can land in any of it,
        # the quarantine discipline is broken
        try:
            got = mgr.alloc_blocks(777, mgr.n_blocks)  # tpr: allow(kv)
        except _kv.KvArenaFull:
            state["probe"].append("full")
            return
        for b in got:
            mgr.block_view(b)[:len(_SENT)] = _SENT
        state["probe_blocks"].extend(got)

    def check(state):
        net, mgr = state["net"], state["mgr"]
        net.assert_delivered()
        if state["reap"] == ["offer-never-arrived"]:
            raise SchedViolation(
                "OFFER never reached the receiver though no partition or "
                "receiver crash was injected — message lost")
        if state.get("q_after_reap") != 2:
            raise SchedViolation(
                "TTL reap of a dead sender's pending handoff must "
                "QUARANTINE its claimed blocks (a one-sided write may "
                "still be in flight); quarantined_count=="
                f"{state.get('q_after_reap')} after reap={state['reap']}")
        for b in state["probe_blocks"]:
            if bytes(mgr.block_view(b)[:len(_SENT)]) != _SENT:
                raise SchedViolation(
                    f"stale one-sided write from the dead sender landed "
                    f"in re-leased block {b} — corruption the quarantine "
                    "exists to prevent")
        _accounted(mgr)

    def teardown(state):
        state["net"].close()
        try:
            state["decode"].close()
            state["mgr"].close()
        except Exception:
            pass

    return _with_couriers(
        "simnet-kvship-death", setup, [("P", sender), ("D", receiver)],
        check, [_module_file(_disagg), _mutants_file()], teardown,
        ["P", "D"])


# ---------------------------------------------------------------------------
# Scenario 3: adoption races a cross-node drain on the real scheduler.
# ---------------------------------------------------------------------------

def _adopt_drain_scenario() -> Scenario:
    """A real paged :class:`DecodeScheduler` (its daemon step loop
    stubbed; a driver pumps boundaries) adopts a shipped sequence while
    a controller node delivers ``drain`` through the transport seam.
    Declared liveness invariant: the adoption is refused AT THE GATE or
    the sequence RETIRES — accepted-then-dropped is the
    ``drain_drops_resumable`` mutant's bug (a migrated sequence silently
    killed by the very drain that migrated it)."""
    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.serving import kv as _kv
    from tpurpc.serving import scheduler as _smod

    def setup(sched: _Scheduler):
        net = SimNet(sched, ["D", "C"])
        orig = _smod.DecodeScheduler._step_loop
        _smod.DecodeScheduler._step_loop = lambda self: None
        mgr = _kv.KvBlockManager(n_blocks=16,
                                 block_bytes=_kv.ENTRY_BYTES * 2,
                                 kind="local", name="simnet-adopt")
        model = ToyDecodeModel()
        dec = _smod.DecodeScheduler(model, max_batch=4, idle_wait_s=0.001,
                                    kv=mgr, name="sim-adopt")
        prompt = np.arange(3, 7, dtype=np.int32)
        kv1, _hit = mgr.alloc_for_prompt(4242, prompt)  # tpr: allow(kv)
        first = model.prefill_paged([prompt], [kv1])
        ctl = object()
        net.route(ctl, "D")
        net.install()
        return {"net": net, "mgr": mgr, "dec": dec, "kv1": kv1,
                "prompt": prompt, "ctl": ctl, "orig_loop": orig,
                "last_token": int(np.asarray(first).ravel()[0])}

    def adopter(state):
        dec, net = state["dec"], state["net"]
        try:
            stream = dec.submit_adopted(
                state["kv1"], state["prompt"],
                last_token=state["last_token"], emitted=1, max_tokens=3)
        except _smod.DrainingError:
            state["outcome"] = "refused-at-gate"
            state["mgr"].free_blocks(state["kv1"])
            return
        for _ in range(300):
            try:
                tok = stream.next(timeout=0)
            except StopIteration:
                state["outcome"] = "retired"
                return
            except _smod.DrainingError as exc:
                state["outcome"] = f"dropped-after-accept: {exc}"
                return
            except Exception as exc:
                state["outcome"] = f"failed: {exc!r}"
                return
            if tok is None:
                net.settle()
        state["outcome"] = "no-terminal"

    def pump(state):
        dec = state["dec"]
        for _ in range(400):
            if state.get("outcome"):
                return
            dec._boundary()
            if dec._running:
                dec._run_step()
        state["pump_exhausted"] = True

    def drainer(state):
        _transport.dispatch("frame", state["ctl"], state["dec"].drain)

    def check(state):
        net = state["net"]
        net.assert_delivered()
        outcome = state.get("outcome")
        if outcome not in ("retired", "refused-at-gate"):
            raise SchedViolation(
                "adopted sequence neither retired nor was refused at the "
                f"gate: {outcome!r} — drain must FINISH what it already "
                "accepted (resumable sequences ride out a drain)")
        dec = state["dec"]
        live = [s.sid for s in (list(dec._running) + list(dec._waiting)
                                + list(dec._swapped))]
        if live:
            raise SchedViolation(
                f"scheduler quiesced with live sequences {live} after a "
                "terminal stream outcome")
        _accounted(state["mgr"])

    def teardown(state):
        state["net"].close()
        _smod.DecodeScheduler._step_loop = state["orig_loop"]
        try:
            state["dec"].close(timeout=1.0)
            state["mgr"].close()
        except Exception:
            pass

    return _with_couriers(
        "simnet-adopt-drain", setup,
        [("D", adopter), ("D", pump), ("C", drainer)], check,
        [_module_file(_smod), _mutants_file()], teardown, ["D", "C"],
        max_steps=300000)


# ---------------------------------------------------------------------------
# Scenario 4: the ctrl-ring park/kick handshake across a partition.
# ---------------------------------------------------------------------------

def _ctrl_kick_scenario() -> Scenario:
    """Producer A posts into consumer B's real ring (shared-memory
    stores land immediately) while the FRAMED plane — which carries the
    wake-up kick — is partitioned and later healed. The consumer drains,
    parks, re-drains once (the mandatory lost-wakeup close), then blocks
    UNTIMED on the kick. Declared invariants: both records arrive in
    order, and the consumer always wakes — a skipped kick (the
    ``ctrl_kick_skipped`` mutant) must surface as the explorer's
    deadlock violation with the pick trace, never as a silent hang."""
    from tpurpc.core import ctrlring as _ctrl

    def setup(sched: _Scheduler):
        if not _ctrl.enabled():
            raise RuntimeError("ctrl ring disabled in this environment")
        net = SimNet(sched, ["A", "B"])
        plane_b = _ctrl.CtrlPlane("simnet-b", kind="local")
        plane_a = _ctrl.CtrlPlane("simnet-a", kind="local")
        if plane_b.rx is None or not plane_a.on_hello(plane_b.hello_blob()):
            raise RuntimeError("simnet: local ring adoption failed")
        wake = SchedEvent(sched, "simnet-ctrl-wake")
        net.route(plane_a, "B")
        net.install()
        return {"net": net, "pa": plane_a, "pb": plane_b, "wake": wake,
                "records": [], "posted": []}

    def producer(state):
        net, pa, wake = state["net"], state["pa"], state["wake"]
        net.partition("A", "B")
        ok1 = pa.post(1, 7, b"x1", 0, wake.set)
        net.heal("A", "B")
        ok2 = pa.post(2, 7, b"x2", 0, wake.set)
        state["posted"] = [ok1, ok2]

    def consumer(state):
        pb, wake, records = state["pb"], state["wake"], state["records"]

        def on_op(op, sid, payload):
            records.append((op, bytes(payload)))

        far = lambda: 1 << 30
        for _ in range(200):
            if len(records) >= 2:
                return
            if pb.drain(on_op, far):
                continue
            pb.park()
            if pb.drain(on_op, far):  # the mandatory post-park re-drain
                pb.unpark()
                continue
            wake.wait()  # untimed: a lost kick IS a reported deadlock
            wake.clear()
            pb.unpark()
        state["spun_out"] = True

    def check(state):
        state["net"].assert_delivered()
        if state.get("spun_out"):
            raise SchedViolation(
                "ctrl consumer spun without making progress")
        if state["posted"] != [True, True]:
            raise SchedViolation(
                f"ring posts did not all place: {state['posted']}")
        if state["records"] != [(1, b"x1"), (2, b"x2")]:
            raise SchedViolation(
                "ring records lost or reordered: "
                f"{state['records']} != [(1, b'x1'), (2, b'x2')]")

    def teardown(state):
        state["net"].close()
        for key in ("pa", "pb"):
            plane = state.get(key)
            close = getattr(plane, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    return _with_couriers(
        "simnet-ctrl-kick", setup,
        [("A", producer), ("B", consumer)], check,
        [_module_file(_ctrl), _mutants_file()], teardown, ["A", "B"])


# ---------------------------------------------------------------------------
# Scenario 5: DisaggDecode.close races an in-flight COMPLETE — the real
# interleaving bug this simulator surfaced (and disagg now fixes).
# ---------------------------------------------------------------------------

def _close_complete_scenario() -> Scenario:
    """P ships a handoff while D closes the decode server. Every
    interleaving is legal EXCEPT a leak: after quiesce the registries of
    a closed server are empty and every arena block is free, quarantined
    or prefix-cached. The pre-fix ``on_complete`` (kept as the
    ``close_leaks_inflight_complete`` mutant) parked the sequence into a
    registry ``close()`` had already swept — blocks leaked forever; the
    fix re-checks ``_closed`` under the lock at the park insert and
    refuses with UNAVAILABLE."""
    from tpurpc.serving import disagg as _disagg
    from tpurpc.serving import kv as _kv

    def setup(sched: _Scheduler):
        net = SimNet(sched, ["P", "D"])
        prompt, payload, entries = _ship_payload(4)
        mgr = _kv.KvBlockManager(n_blocks=8,
                                 block_bytes=_kv.ENTRY_BYTES * 2,
                                 kind="local", name="simnet-close")
        decode = _disagg.DisaggDecode(_StubSched("sim-close"), mgr)
        chan = SimChannel(net, "P", "D", {
            _disagg._method("OfferKv"): decode.on_offer,
            _disagg._method("CompleteKv"): decode.on_complete,
            _disagg._method("ReleaseKv"): decode.on_release,
        })
        shipper = _disagg._KvShipper(chan)
        net.default_route("P", "D")
        net.install()
        return {"net": net, "mgr": mgr, "decode": decode,
                "shipper": shipper, "prompt": prompt, "payload": payload,
                "sent": [], "err": []}

    def sender(state):
        sh = state["shipper"]
        try:
            grant, handoff, _pos, _rh, _rf = sh.offer(
                503, state["prompt"], 4, timeout=5.0)
            sh.ship(grant, handoff, memoryview(state["payload"]), 4,
                    last_token=4, emitted=1, timeout=5.0)
            state["sent"].append(handoff)
        except (SimRpcError, OSError) as exc:
            state["err"].append(repr(exc))

    def closer(state):
        state["decode"].close()

    def check(state):
        net, decode, mgr = state["net"], state["decode"], state["mgr"]
        net.assert_delivered()
        if net.handler_faults:
            raise SchedViolation(f"handler faults: {net.handler_faults}")
        if decode._pending or decode._parked:
            raise SchedViolation(
                "closed server's registries not empty at quiesce: "
                f"pending={list(decode._pending)} "
                f"parked={list(decode._parked)} — the close/complete "
                "race parked into a swept registry (blocks leak forever)")
        _accounted(mgr)
        if not state["sent"] and not state["err"]:
            raise SchedViolation(
                "ship neither succeeded nor failed with attribution")

    def teardown(state):
        state["net"].close()
        try:
            state["decode"].close()
            state["mgr"].close()
        except Exception:
            pass

    return _with_couriers(
        "simnet-close-complete", setup,
        [("P", sender), ("D", closer)], check,
        [_module_file(_disagg), _mutants_file()], teardown, ["P", "D"])


# ---------------------------------------------------------------------------
# Scenario 6: live migration, source scheduler to destination decode.
# ---------------------------------------------------------------------------

def _migrate_scenario() -> Scenario:
    """The full ``migrate()`` path over the simulated fabric: a sequence
    decoding on source node S (real paged scheduler, pumped) is frozen,
    detached at a boundary, offered/shipped/completed to node D's real
    ``DisaggDecode``. Declared invariants: exactly one terminal stream
    record (migrated — never lost, never ALSO still live at the source),
    byte-identical KV at the destination, and both arenas conserved."""
    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.serving import disagg as _disagg
    from tpurpc.serving import kv as _kv
    from tpurpc.serving import scheduler as _smod

    def setup(sched: _Scheduler):
        net = SimNet(sched, ["S", "D"])
        orig = _smod.DecodeScheduler._step_loop
        _smod.DecodeScheduler._step_loop = lambda self: None
        mgr_s = _kv.KvBlockManager(n_blocks=16,
                                   block_bytes=_kv.ENTRY_BYTES * 2,
                                   kind="local", name="simnet-mig-src")
        mgr_d = _kv.KvBlockManager(n_blocks=16,
                                   block_bytes=_kv.ENTRY_BYTES * 2,
                                   kind="local", name="simnet-mig-dst")
        model = ToyDecodeModel()
        sched_s = _smod.DecodeScheduler(model, max_batch=4,
                                        idle_wait_s=0.001, kv=mgr_s,
                                        name="sim-mig-src")
        src_state = _disagg.DisaggDecode(sched_s, mgr_s)
        decode_d = _disagg.DisaggDecode(_StubSched("sim-mig-dst"), mgr_d)
        chan = SimChannel(net, "S", "D", {
            _disagg._method("OfferKv"): decode_d.on_offer,
            _disagg._method("CompleteKv"): decode_d.on_complete,
            _disagg._method("ReleaseKv"): decode_d.on_release,
        })
        prompt = np.arange(11, 15, dtype=np.int32)
        stream = sched_s.submit(prompt, max_tokens=8)
        net.default_route("S", "D")
        net.install()
        return {"net": net, "mgr_s": mgr_s, "mgr_d": mgr_d,
                "sched_s": sched_s, "src_state": src_state,
                "decode_d": decode_d, "chan": chan, "stream": stream,
                "orig_loop": orig, "snap": []}

    def pump(state):
        dec, net = state["sched_s"], state["net"]
        for _ in range(500):
            if state.get("done"):
                return
            dec._boundary()
            if not state.get("freeze") and dec._running:
                dec._run_step()
            if not state.get("freeze") and state["stream"].emitted >= 2:
                state["freeze"] = True
            net.settle()
        state["pump_exhausted"] = True

    def migrator(state):
        net = state["net"]
        for _ in range(400):
            if state.get("freeze"):
                break
            net.settle()
        else:
            state["mig"] = ("never-froze",)
            return
        sid = state["stream"].sid
        seq = next((s for s in list(state["sched_s"]._running)
                    if s.sid == sid), None)
        if seq is not None and seq.kv is not None:
            state["snap"] = [seq.kv.entry(i)
                             for i in range(seq.kv.length)]
        moved, failed = _disagg.migrate(
            state["src_state"], state["chan"], "sim-dst:0", sids=[sid],
            timeout_s=5.0)
        state["mig"] = (moved, failed)

    def reader(state):
        stream, net = state["stream"], state["net"]
        for _ in range(600):
            try:
                tok = stream.next(timeout=0)
            except StopIteration:
                state["outcome"] = ("retired",)
                break
            except _disagg.SeqMigrated as m:
                state["outcome"] = ("migrated", m.seq_key, m.next_index)
                break
            except _disagg.MigrationFailed as exc:
                state["outcome"] = ("failed", str(exc))
                break
            except Exception as exc:
                state["outcome"] = ("error", repr(exc))
                break
            if tok is None:
                net.settle()
        else:
            state["outcome"] = ("no-terminal",)
        state["done"] = True

    def check(state):
        net = state["net"]
        net.assert_delivered()
        if net.handler_faults:
            raise SchedViolation(f"handler faults: {net.handler_faults}")
        if state.get("pump_exhausted"):
            raise SchedViolation("source pump exhausted before quiesce")
        if state.get("mig") != (1, 0):
            raise SchedViolation(
                f"migrate() did not move exactly the one sequence: "
                f"{state.get('mig')}")
        outcome = state.get("outcome")
        if not outcome or outcome[0] != "migrated":
            raise SchedViolation(
                "source stream did not end with the re-attach record: "
                f"{outcome!r} — the sequence was lost across migration")
        parked = state["decode_d"]._parked
        if len(parked) != 1 or outcome[1] not in parked:
            raise SchedViolation(
                f"destination parked registry {list(parked)} does not "
                f"hold exactly the migrated key {outcome[1]} — sequence "
                "lost or duplicated")
        sid = state["stream"].sid
        if any(s.sid == sid for s in list(state["sched_s"]._running)):
            raise SchedViolation(
                "sequence still live at the source AFTER migrating — "
                "duplicated execution")
        snap = state["snap"]
        pk = parked[outcome[1]]
        got = [pk.kv.entry(i) for i in range(pk.kv.length)]
        if not snap or got != snap:
            raise SchedViolation(
                f"KV content diverged across migration: {len(got)} "
                f"entries at destination vs snapshot of {len(snap)}")
        _accounted(state["mgr_s"])
        _accounted(state["mgr_d"],
                   owners=[p.kv for p in parked.values()])

    def teardown(state):
        state["net"].close()
        _smod.DecodeScheduler._step_loop = state["orig_loop"]
        try:
            state["sched_s"].close(timeout=1.0)
            state["decode_d"].close()
            state["mgr_s"].close()
            state["mgr_d"].close()
        except Exception:
            pass

    return _with_couriers(
        "simnet-migrate", setup,
        [("S", pump), ("S", migrator), ("S", reader)], check,
        [_module_file(_disagg), _mutants_file()], teardown, ["S", "D"],
        max_steps=400000)


# ---------------------------------------------------------------------------
# Registry + suite faces (mirrors tpurpc.analysis.schedule).
# ---------------------------------------------------------------------------

#: scenario name -> zero-arg factory (fresh Scenario per exploration)
SIM_SCENARIOS: Dict[str, Callable[[], Scenario]] = {
    "simnet-kvship": _kvship_scenario,
    "simnet-kvship-death": _kvship_death_scenario,
    "simnet-adopt-drain": _adopt_drain_scenario,
    "simnet-ctrl-kick": _ctrl_kick_scenario,
    "simnet-close-complete": _close_complete_scenario,
    "simnet-migrate": _migrate_scenario,
}


def _mutants():
    from tpurpc.analysis import simmutants

    return simmutants.SIM_MUTANTS


def run_scenario(name: str, preemption_bound: int = 2,
                 max_schedules: int = 20000,
                 mutant: Optional[str] = None) -> ExploreResult:
    """Explore one named simnet scenario, optionally with a seeded
    real-code distributed mutant applied for the duration."""
    scenario = SIM_SCENARIOS[name]()
    if mutant is None:
        return explore(scenario, preemption_bound, max_schedules)
    m = _mutants()[mutant]
    if m.scenario != name:
        raise ValueError(f"mutant {mutant} targets scenario {m.scenario}, "
                         f"not {name}")
    with m.applied():
        return explore(scenario, preemption_bound, max_schedules)


def quick_suite(preemption_bound: int = 1, max_schedules: int = 200,
                mutant_bound: int = 2, mutant_schedules: int = 4000,
                verbose: bool = False) -> List[ExploreResult]:
    """The check.sh ``simnet-quick`` stage: every scenario explored clean
    at the given bound, every seeded distributed mutant killed. Mutants
    search at ``mutant_bound`` with a deeper schedule budget — the
    close/complete leak needs the courier preempted inside the unlocked
    ``set_length`` window, which bound 1's DFS prefix order reaches only
    ~1.2k schedules in. Sized to a ~30 s budget; the full-depth runs
    live in tests/test_simnet.py."""
    out: List[ExploreResult] = []
    for name in sorted(SIM_SCENARIOS):
        res = run_scenario(name, preemption_bound, max_schedules)
        if verbose:
            print(f"simnet: {res!r}")
        out.append(res)
    for mname, m in sorted(_mutants().items()):
        res = run_scenario(m.scenario, mutant_bound, mutant_schedules,
                           mutant=mname)
        # a mutant result is GOOD when a violation was found
        res = ExploreResult(f"mutant:{mname}", not res.ok, res.schedules,
                            res.violation, res.steps, res.capped,
                            res.preemption_bound)
        if verbose:
            kill = "KILLED" if res.ok else "SURVIVED"
            print(f"simnet: mutant {mname}: {kill} "
                  f"({res.schedules} schedules)")
        out.append(res)
    return out


def mutant_kill_suite(preemption_bound: int = 2,
                      max_schedules: int = 20000,
                      verbose: bool = False) -> Dict[str, bool]:
    """killed-by-exploration per seeded distributed mutant (acceptance:
    every one True, and the clean scenarios must pass)."""
    kills: Dict[str, bool] = {}
    for mname, m in sorted(_mutants().items()):
        res = run_scenario(m.scenario, preemption_bound, max_schedules,
                           mutant=mname)
        kills[mname] = res.violation is not None
        if verbose:
            print(f"simnet mutant {mname}: "
                  f"{'KILLED' if kills[mname] else 'SURVIVED'} "
                  f"({res.schedules} schedules, {res.steps} steps)")
    return kills
