"""Runtime lock-order detection: the ``CheckedLock`` shim.

Opt-in via ``TPURPC_DEBUG_LOCKS=1``. When disabled (the default) the
``make_lock``/``make_condition`` factories hand back plain ``threading``
primitives — zero overhead, byte-identical hot paths. When enabled, every
factory-made lock is a :class:`CheckedLock` that:

* records the **cross-thread acquisition graph**: an edge ``A → B`` whenever
  a thread acquires ``B`` while holding ``A``. Locks are keyed by *name*
  (``Class._attr``), not identity — every instance of a class contributes to
  one graph node, exactly like kernel lockdep's lock classes, so a cycle is
  reported the first time two code paths disagree about order, without ever
  needing the actual deadlock to fire.
* reports **cycles** in that graph as potential deadlocks (recorded in
  :func:`lock_violations`, logged once per distinct cycle).
* flags **locks held across blocking calls**: ``Condition.wait`` while
  holding any *other* checked lock, and any call site instrumented with
  :func:`note_blocking` (selector ``select``, bootstrap socket reads).

The existing test suite exercises the instrumented modules
(poller/pair/xds/channel/channelz); run it under ``TPURPC_DEBUG_LOCKS=1``
to sweep for ordering regressions (``tools/check.sh`` does).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

#: read once at import: the factories must cost nothing when disabled
ENABLED = os.environ.get("TPURPC_DEBUG_LOCKS", "") == "1"

_tls = threading.local()

_graph_mu = threading.Lock()
#: name -> set of names acquired while holding it (the order graph)
_edges: Dict[str, Set[str]] = {}
_violations: List[str] = []
_reported: Set[Tuple[str, ...]] = set()


def _held() -> List["CheckedLock"]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _record_violation(msg: str) -> None:
    from tpurpc.utils.trace import log_error

    with _graph_mu:
        _violations.append(msg)
    log_error("TPURPC_DEBUG_LOCKS: %s", msg)


def _find_cycle(src: str, dst: str) -> Optional[List[str]]:
    """After adding edge src→dst: a path dst→…→src closes a cycle.
    Caller holds ``_graph_mu``."""
    stack = [(dst, [dst])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == src:
            return path + [src] if node != dst or len(path) > 1 else [dst, src]
        if node in seen:
            continue
        seen.add(node)
        for nxt in _edges.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _note_acquire_edge(lock: "CheckedLock") -> None:
    held = _held()
    for h in held:
        if h is lock or h.name == lock.name:
            continue
        with _graph_mu:
            peers = _edges.setdefault(h.name, set())
            if lock.name in peers:
                continue
            peers.add(lock.name)
            cycle = _find_cycle(h.name, lock.name)
        if cycle:
            key = tuple(sorted(set(cycle)))
            with _graph_mu:
                fresh = key not in _reported
                _reported.add(key)
            if fresh:
                _record_violation(
                    "lock-order cycle (potential deadlock): "
                    + " -> ".join([h.name] + cycle)
                    + f" (thread {threading.current_thread().name})")


class CheckedLock:
    """``threading.Lock`` wrapper feeding the acquisition-order graph.

    Non-reentrant, same semantics as the lock it wraps; re-acquiring it on
    the same thread is reported (and would deadlock) — use
    :func:`make_rlock` for reentrant use."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = (threading.RLock() if self._reentrant
                       else threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        if (not self._reentrant and blocking
                and any(h is self for h in held)):
            _record_violation(
                f"self-deadlock: {self.name} re-acquired by holding thread "
                f"{threading.current_thread().name}")
            raise RuntimeError(
                f"re-acquire of non-reentrant checked lock {self.name}")
        _note_acquire_edge(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(self)
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name}>"

    # threading.Condition(lock) uses these when the lock provides them; the
    # default release()/acquire() round-trip keeps our bookkeeping correct,
    # so no _release_save/_acquire_restore overrides are needed.


class CheckedRLock(CheckedLock):
    _reentrant = True


class CheckedCondition(threading.Condition):
    """Condition over a CheckedLock that flags waits while other checked
    locks are held — a parked waiter holding an unrelated lock is the
    round-5 ``wait_event`` parked-waiter bug class."""

    def __init__(self, name: str, lock=None):
        self.name = name
        super().__init__(lock if lock is not None else CheckedLock(name))

    def wait(self, timeout: Optional[float] = None) -> bool:
        others = [h.name for h in _held()
                  if h is not self._lock]
        if others:
            _record_violation(
                f"cv-wait on {self.name} while holding {', '.join(others)} "
                "(lock held across a blocking wait)")
        return super().wait(timeout)
    # wait_for() funnels through wait(); notify paths need no bookkeeping.


# -- factories (the wiring surface) ------------------------------------------

#: tpurpc-proof (ISSUE 12): the deterministic schedule explorer
#: (:mod:`tpurpc.analysis.schedule`) intercepts the factories here — the
#: SAME seam TPURPC_DEBUG_LOCKS rides — so scenario objects built while an
#: exploration is active get scheduler-controlled primitives and every
#: lock/condition operation becomes a scheduling point. ``None`` (the
#: default, and the only value outside an active exploration) costs one
#: global load per factory call, all of them at object-construction time.
_factory_hook = None


def set_factory_hook(hook) -> None:
    """Install (or clear, with ``None``) the exploration factory hook:
    ``hook(kind, name, lock)`` with kind in ``("lock", "rlock",
    "condition", "event")`` returns a primitive or ``None`` to decline
    (the factory then falls through to its normal product)."""
    global _factory_hook
    _factory_hook = hook


def make_lock(name: str):
    """A mutex for ``name`` (``Class._attr``): plain ``threading.Lock``
    normally, :class:`CheckedLock` under ``TPURPC_DEBUG_LOCKS=1``."""
    if _factory_hook is not None:
        got = _factory_hook("lock", name, None)
        if got is not None:
            return got
    return CheckedLock(name) if ENABLED else threading.Lock()


def make_rlock(name: str):
    if _factory_hook is not None:
        got = _factory_hook("rlock", name, None)
        if got is not None:
            return got
    return CheckedRLock(name) if ENABLED else threading.RLock()


def make_condition(name: str, lock=None):
    """A condition variable; pass ``lock`` to share an existing factory-made
    lock (the Condition then guards the same graph node)."""
    if _factory_hook is not None:
        got = _factory_hook("condition", name, lock)
        if got is not None:
            return got
    if not ENABLED:
        return threading.Condition(lock)
    return CheckedCondition(name, lock)


def make_event(name: str):
    """An event for ``name`` (``Class._attr``): plain ``threading.Event``
    normally; under an active schedule exploration the factory hook hands
    back a scheduler-controlled event so ``wait()`` parks cooperatively
    instead of stalling the explorer on a wall-clock timeout."""
    if _factory_hook is not None:
        got = _factory_hook("event", name, None)
        if got is not None:
            return got
    return threading.Event()


def checked_condition(name: str, lock=None) -> CheckedCondition:
    """Always-checked variant (tests use this regardless of ENABLED)."""
    return CheckedCondition(name, lock)


def note_blocking(what: str) -> None:
    """Instrument a blocking call site: any checked lock held here is a
    latency/deadlock hazard (the selector ``select`` in the waiter path, the
    bootstrap blob reads). No-op unless debugging is enabled AND a checked
    lock is actually held."""
    if not ENABLED:
        return
    held = _held()
    if held:
        _record_violation(
            f"lock(s) {', '.join(h.name for h in held)} held across "
            f"blocking call: {what} "
            f"(thread {threading.current_thread().name})")


def lock_violations() -> List[str]:
    with _graph_mu:
        return list(_violations)


def acquisition_graph() -> Dict[str, Set[str]]:
    with _graph_mu:
        return {k: set(v) for k, v in _edges.items()}


def reset_lock_state() -> None:
    """Clear the graph and recorded violations (tests)."""
    with _graph_mu:
        _edges.clear()
        _violations.clear()
        _reported.clear()
