"""tpurpc-proof: seeded REAL-CODE concurrency mutants for the explorer.

Each mutant here is a faithful copy of a live method with exactly one
concurrency discipline removed — a hoisted publish, a deleted lock, a
skipped death-path quarantine. :mod:`tpurpc.analysis.schedule` must find
every one of them *by exploration* (a violating interleaving, not a
sequential unit test): that is the proof the explorer has teeth, and the
"runtime matches model" guarantee ringcheck's hand-written models alone
cannot give.

This module's file is added to the instrumented set whenever a mutant is
active, so the mutated lines get the same line-granular scheduling
points as the originals.

The copies are deliberately line-for-line with their sources (see each
docstring for the source function) so the ONLY behavioral difference is
the seeded bug; drift between a mutant and its source weakens the kill
claim, nothing else.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict

__all__ = ["Mutant", "SCHED_MUTANTS"]


class Mutant:
    """One seeded real-code mutant: ``applied()`` patches ``target.attr``
    to the mutated copy for the duration of an exploration."""

    def __init__(self, name: str, scenario: str, target, attr: str,
                 repl, description: str):
        self.name = name
        self.scenario = scenario
        self.target = target
        self.attr = attr
        self.repl = repl
        self.description = description

    @contextlib.contextmanager
    def applied(self):
        orig = getattr(self.target, self.attr)
        setattr(self.target, self.attr, self.repl)
        try:
            yield self
        finally:
            setattr(self.target, self.attr, orig)


# ---------------------------------------------------------------------------
# handoff_publish_before_store — HandoffRing.publish with the commit stamp
# HOISTED above the payload store (the modeled handoff_commit_before_write
# mutant, seeded into the implementation).
# ---------------------------------------------------------------------------

def _handoff_publish_before_store(self, item, timeout=None):
    t = next(self._ticket)
    slot = t % self._cap
    deadline = None if timeout is None else time.monotonic() + timeout
    while self._seq[slot] != t:
        if self._closed:
            return False
        if deadline is not None and time.monotonic() >= deadline:
            return False
        self._space_evt.wait(0.01)
        self._space_evt.clear()
    if self._closed:
        return False
    self._seq[slot] = t + 1  # MUTANT: publish hoisted above the payload
    self._slots[slot] = item
    self._data_evt.set()
    return True


# ---------------------------------------------------------------------------
# scheduler_unlocked_submit — DecodeScheduler.submit with `with self._lock`
# REMOVED: the waiting-queue append races the boundary's locked
# decide/clear/extend edit (lost submits, deque-mutated-during-iteration).
# ---------------------------------------------------------------------------

def _scheduler_unlocked_submit(self, prompt, *, max_tokens=32, slo=None):
    import numpy as np

    from tpurpc.serving import scheduler as _smod

    slo = slo if slo is not None else _smod.SLO_INTERACTIVE
    if slo not in _smod._SLO_CODE:
        raise ValueError(f"unknown slo class {slo!r}")
    prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
    seq = _smod._Seq(next(self._sids), prompt, max(1, int(max_tokens)), slo)
    # MUTANT: the lock is gone — everything below raced the boundary
    if self._closed:
        raise RuntimeError("scheduler closed")
    if self._draining or (self._draining_fn is not None
                          and self._draining_fn()):
        raise _smod.DrainingError("scheduler draining")
    reason, pushback = self._shed_decision_locked(slo)
    if reason is not None:
        self.shed_total += 1
        raise _smod.ShedError(reason, pushback, slo)
    self._waiting.append(seq)
    self._kick.notify_all()
    return _smod.TokenStream(seq, self)


# ---------------------------------------------------------------------------
# rdv_death_no_quarantine — RdvLink.close with the death-path DISCARD
# dropped: claimed regions go back to the pool free list, where the
# straggling writer the quarantine exists for can corrupt the next lease.
# ---------------------------------------------------------------------------

def _rdv_close_no_quarantine(self):
    from tpurpc.core.rendezvous import window_share
    from tpurpc.obs import flight as _flight

    with self._lock:
        if self.closed:
            return
        self.closed = True
        leases = list(self._leases.values())
        self._leases.clear()
        self._req_lease.clear()
        self._pregrants_out.clear()
        self._grants.clear()
        windows = list(self._windows.items())
        self._windows.clear()
        self._window_order = []
        self._cond.notify_all()
    for lease in leases:
        _flight.emit(_flight.RDV_RELEASE, self._ftag,
                     lease.lease_id, 0)
        lease.release(discard=False)  # MUTANT: quarantine skipped
    for (kind, handle), win in windows:
        window_share().release(kind, handle, win)


# ---------------------------------------------------------------------------
# kv_free_unlocked — KvBlockManager.free_blocks with the refcount lock
# REMOVED: the read-modify-write decrement races a concurrent prefix-cache
# eviction's decrement, and a lost update strands blocks as phantom-used
# arena memory forever.
# ---------------------------------------------------------------------------

def _kv_free_unlocked(self, kv, cache_prefix=False):
    from tpurpc.serving.kv import FLAG_POISONED, _PrefixEntry

    if kv.host is not None:
        with self._lock:
            self._swapped_blocks.pop(kv.key, None)
        kv.host = None
    if not kv.blocks:
        kv.length = 0
        return
    donate = None
    if (cache_prefix and kv.prefix_key is not None
            and kv.length >= kv.prefix_span > 0):
        h, _tok, flags = kv.entry(kv.prefix_span - 1)
        if not flags & FLAG_POISONED:
            bt = self.block_tokens
            span_blocks = tuple(kv.blocks[:kv.prefix_span // bt])
            donate = (kv.prefix_key,
                      _PrefixEntry(span_blocks, kv.prefix_span, h, flags))
    blocks, kv.blocks = kv.blocks, []
    kv.length = 0
    kv.shared_len = 0
    # MUTANT: the lock is gone — each decrement below is a racy
    # read-modify-write against a concurrent eviction's decrement
    if donate is not None and donate[0] not in self._prefix:
        self._prefix[donate[0]] = donate[1]
        for b in donate[1].blocks:
            self._refs[b] += 1
    for b in blocks:
        r = self._refs.get(b, 0) - 1
        if r > 0:
            self._refs[b] = r
            continue
        self._refs.pop(b, None)
        self._owner.pop(b, None)
        self._free.append(b)


# ---------------------------------------------------------------------------
# park_lost_wakeup — Pair._complete_park with the post-ack readable()/
# has_message() re-check REMOVED: a byte that lands between our park
# decision and the peer's window-close+ack is stranded when the reader
# and rings are released to the pool (the park-decide vs incoming-byte
# race the re-check exists for).
# ---------------------------------------------------------------------------

def _park_lost_wakeup(self):
    from tpurpc.core.pair import PairState, RingPool
    from tpurpc.core.pair import _flight, _stats, trace_ring

    released = 0
    aborted = False
    with self._park_lock:
        if not self._park_pending:
            return
        self._park_pending = False
        if self.state is not PairState.CONNECTED:
            return
        try:
            # _recv_guard RAISES on concurrent entry: a receiver mid-
            # drain means the pair is not idle — abort, don't block
            with self._recv_guard:
                # MUTANT: the readable()/has_message() re-check is gone —
                # bytes that landed between the park decision and the
                # peer's ack are stranded when the reader is released
                pool = RingPool.get()
                if self.reader is not None:
                    self.reader.release()
                    self.reader = None
                self._status_np = None
                for attr in ("recv_region", "status_region"):
                    region = getattr(self, attr)
                    if region is not None:
                        setattr(self, attr, None)
                        try:
                            released += len(region.buf)
                        except ValueError:
                            pass
                        pool.release(region)
                self._published_head_mirror = 0
                self._parked = True
                self.parked_epochs += 1
        except AssertionError:
            aborted = True
    if aborted:
        self._send_rearm(retained=True)
        self.kick()
        return
    _flight.emit(_flight.PAIR_PARK, self._ftag, released)
    _stats.counter_inc("pair_park")
    from tpurpc.core.poller import Poller

    Poller.note_parked(self)
    trace_ring.log("pair %s parked (%d ring bytes pooled)",
                   self.tag, released)


def _targets():
    from tpurpc.core.handoff import HandoffRing
    from tpurpc.core.pair import Pair
    from tpurpc.core.rendezvous import RdvLink
    from tpurpc.serving.kv import KvBlockManager
    from tpurpc.serving.scheduler import DecodeScheduler

    return HandoffRing, DecodeScheduler, RdvLink, KvBlockManager, Pair


def _build() -> Dict[str, Mutant]:
    (HandoffRing, DecodeScheduler, RdvLink, KvBlockManager,
     Pair) = _targets()
    muts = [
        Mutant("handoff_publish_before_store", "handoff-mpmc",
               HandoffRing, "publish", _handoff_publish_before_store,
               "commit stamp stored before the payload: the consumer can "
               "pass the gate and read an unwritten slot"),
        Mutant("scheduler_unlocked_submit", "scheduler-admission",
               DecodeScheduler, "submit", _scheduler_unlocked_submit,
               "submit appends to the waiting queue without _lock: the "
               "boundary's clear/extend edit loses it"),
        Mutant("rdv_death_no_quarantine", "rendezvous-death",
               RdvLink, "close", _rdv_close_no_quarantine,
               "peer-death release pools the claimed region instead of "
               "discarding it: a straggling writer corrupts the next lease"),
        Mutant("kv_free_unlocked", "kv-refcount",
               KvBlockManager, "free_blocks", _kv_free_unlocked,
               "unlocked refcount decrement races an eviction: a lost "
               "update strands arena blocks forever"),
        Mutant("park_lost_wakeup", "pair-park",
               Pair, "_complete_park", _park_lost_wakeup,
               "park completion skips the readable re-check: a byte that "
               "raced the park decision is stranded in a pooled ring"),
    ]
    return {m.name: m for m in muts}


#: name -> Mutant (lazy targets resolved at import of this module)
SCHED_MUTANTS: Dict[str, Mutant] = _build()
