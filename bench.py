"""tpurpc headline benchmark: 4MB tensor streaming into jax.Array.

Mirrors the reference's large-payload bandwidth test (RDMA_BP, 128KB–4MB
payloads → 82.6 Gb/s on IB EDR, SURVEY.md §6) recast as the TPU north star:
client streams float32[1024,1024] (4 MiB) tensors over the ring transport;
the server decodes each into a ``jax.Array`` on the default backend (TPU HBM
on real hardware) and acknowledges with total bytes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against the reference's 82.6 Gb/s (= 10.325 GB/s) aggregate
TX bandwidth — measured on InfiniBand EDR hardware; we run whatever link the
bench host gives us (loopback shm rings here).

Env knobs: TPURPC_BENCH_MSGS (default 64 × 4MiB), TPURPC_BENCH_PLATFORM
(default RDMA_BPEV = hybrid-wakeup ring), TPURPC_BENCH_CPU=1 to pin jax to
CPU (CI without a chip).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_GBPS = 82.6 / 8  # reference aggregate bandwidth, GB/s

_SERVER_CODE = r"""
import os, sys
import numpy as np
if os.environ.get("TPURPC_BENCH_CPU") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
from tpurpc.jaxshim import add_tensor_method, to_jax
from tpurpc.rpc.server import Server

def consume(req_iter):
    total = 0
    checksum = 0.0
    for tree in req_iter:
        arr = to_jax(tree["x"])          # host view -> device (HBM on TPU)
        arr.block_until_ready()
        total += arr.nbytes
        checksum += float(arr[0, 0])
    yield {"bytes": np.int64(total), "check": np.float64(checksum)}

srv = Server(max_workers=8)
add_tensor_method(srv, "Sink", consume, kind="stream_stream")
port = srv.add_insecure_port("127.0.0.1:0")
srv.start()
print(port, flush=True)
srv.wait_for_termination(timeout=600)
"""


def main() -> None:
    os.environ.setdefault("GRPC_PLATFORM_TYPE",
                          os.environ.get("TPURPC_BENCH_PLATFORM", "RDMA_BPEV"))
    os.environ.setdefault("GRPC_RDMA_RING_BUFFER_SIZE_KB", "16384")

    n_msgs = int(os.environ.get("TPURPC_BENCH_MSGS", "64"))

    import numpy as np

    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__)) +
                         os.pathsep + env.get("PYTHONPATH", ""))
    srv = subprocess.Popen([sys.executable, "-c", _SERVER_CODE],
                           stdout=subprocess.PIPE,
                           stderr=subprocess.DEVNULL, env=env, text=True)
    try:
        port = int(srv.stdout.readline().strip())

        from tpurpc.jaxshim import TensorClient
        from tpurpc.rpc.channel import Channel

        payload = np.ones((1024, 1024), np.float32)  # 4 MiB
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            # warmup: backend init + jit + ring bring-up out of the timing
            list(cli.duplex("Sink", gen(2), timeout=300))

            t0 = time.perf_counter()
            replies = list(cli.duplex("Sink", gen(n_msgs), timeout=600))
            dt = time.perf_counter() - t0

        total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
        assert total == n_msgs * payload.nbytes, (total, n_msgs)
        gbps = total / dt / 1e9
        print(json.dumps({
            "metric": "stream_4MiB_tensors_to_jax_Array",
            "value": round(gbps, 3),
            "unit": "GB/s",
            "vs_baseline": round(gbps / BASELINE_GBPS, 3),
        }))
    finally:
        srv.kill()


if __name__ == "__main__":
    main()
