"""tpurpc headline benchmark: 4MB tensor streaming into jax.Array.

Mirrors the reference's large-payload bandwidth test (RDMA_BP, 128KB–4MB
payloads → 82.6 Gb/s on IB EDR, SURVEY.md §6) recast as the TPU north star:
client streams float32[1024,1024] (4 MiB) tensors over the ring transport;
the server decodes each into a ``jax.Array`` on the default backend (TPU HBM
on real hardware) and acknowledges with total bytes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against the reference's 82.6 Gb/s (= 10.325 GB/s) aggregate
TX bandwidth — measured on InfiniBand EDR hardware; we run whatever link the
bench host gives us (loopback shm rings here).

Robustness contract (VERDICT r1 #1): the server prints its port *before* jax
backend init, then warms the backend (import jax + device_put + decode jit)
and prints READY; the client budgets that cold start outside every RPC
deadline. Server stderr is captured and surfaced on any failure. If the
default jax platform (axon TPU tunnel) fails to come up within
TPURPC_BENCH_READY_S, the run falls back to JAX_PLATFORMS=cpu so the
benchmark always produces a number.

Env knobs: TPURPC_BENCH_MSGS (default 64 × 4MiB), TPURPC_BENCH_PLATFORM
(default RDMA_BPEV = hybrid-wakeup ring), TPURPC_BENCH_CPU=1 to pin jax to
CPU directly, TPURPC_BENCH_READY_S (default 300) backend warmup budget.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

BASELINE_GBPS = 82.6 / 8  # reference aggregate bandwidth, GB/s

_SERVER_CODE = r"""
import os, sys, time
import numpy as np

from tpurpc.rpc.server import Server

# Two servers (deployment guidance, round 9 / tpurpc-express): the BULK
# sink defaults to the instrumented PYTHON plane because that is where
# the rendezvous bulk-tensor path lives — payloads over the size bar move
# as ONE one-sided write into a pre-granted landing region and the codec
# aliases it in place (ISSUE 9). Same-weather A/B on this rig, 4 MiB
# tensor streams: python+rendezvous 3.6 GB/s vs native framed 1.72 vs
# python framed 0.65 — the rendezvous plane wins by ~2.1x over the
# previous default, so it IS the default; TPURPC_BENCH_SINK_NATIVE=1
# flips back to the native framed plane (the C loop does not speak the
# rendezvous control frames yet — ROADMAP item 5 territory).
srv = Server(max_workers=8,
             native_dataplane=False
             if os.environ.get("TPURPC_BENCH_SINK_NATIVE", "0") == "0"
             else None)
port = srv.add_insecure_port("127.0.0.1:0")
# Serving workers sized for PIPELINED clients (ISSUE 3): a request parks
# its pool worker inside the FanInBatcher until its batch completes, so
# max_workers caps how many requests can even REACH the batcher — 8
# workers flat-lined the depth sweep at one batch in flight. 64 covers
# 8 clients x depth 16 minus the batcher's own bounded pipeline.
srv_infer = Server(max_workers=64)
port_infer = srv_infer.add_insecure_port("127.0.0.1:0")
# Python-dataplane sink for the batch-stats probe: when the MEASURED plane
# is the native one (whose batching is C-side, invisible to the Python
# counters), the client runs one short untimed stream against this server
# so the artifact still carries real drain-batch histograms.
srv_probe = Server(max_workers=2, native_dataplane=False)
port_probe = srv_probe.add_insecure_port("127.0.0.1:0")
print("PORT", port, port_infer, port_probe, flush=True)  # bind first

# Backend bring-up OUTSIDE any RPC deadline. On the axon TPU tunnel this can
# take minutes; the client waits for READY with its own wall budget.
if os.environ.get("TPURPC_BENCH_CPU") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
t0 = time.time()
dev = jax.devices()[0]
x = jax.device_put(np.ones((1024, 1024), np.float32))
x.block_until_ready()
y = (x[:8, :8] + 1.0).block_until_ready()   # trivial compile warm
print("WARM", dev.platform, round(time.time() - t0, 1), file=sys.stderr,
      flush=True)

from tpurpc.jaxshim import FanInBatcher, add_tensor_method, to_jax

def consume(req_iter):
    # Bounded-depth h2d pipeline: receive/decode message k+1 while message
    # k's device_put is in flight (the tunnel moves h2d at ~1 GB/s;
    # overlapping hides ring-transport time behind the transfers). On
    # ACCELERATORS the checksum accumulates ON DEVICE — d2h round trips
    # over the tunnel cost tens-to-hundreds of ms each and are wildly
    # jittery, so the hot loop must contain zero of them; ONE readback
    # happens at stream end. On the CPU fallback that device-side
    # accumulate is ~0.6 ms/message of pure op-dispatch overhead
    # (measured, tpurpc-express round) for arrays the rendezvous path
    # dlpack-ALIASES host-side — a zero-copy numpy read is the same
    # delivery proof at ~1 µs.
    from collections import deque
    import jax.numpy as jnp
    total = 0
    on_cpu = dev.platform == "cpu"
    checksum = jnp.float32(0.0)
    checksum_f = 0.0
    inflight = deque()

    def retire(arr):
        nonlocal total, checksum, checksum_f
        arr.block_until_ready()   # bound in-flight transfers to the deque
        total += arr.nbytes       # depth (deep queues collapse the tunnel)
        if on_cpu:
            checksum_f += float(np.asarray(arr)[0, 0])  # zero-copy read
        else:
            checksum = checksum + arr[0, 0]  # async device-side accumulate

    for tree in req_iter:
        inflight.append(to_jax(tree["x"]))   # async dispatch -> device
        if len(inflight) > 3:
            retire(inflight.popleft())
    while inflight:
        retire(inflight.popleft())
    checksum = checksum + jnp.float32(checksum_f)
    # Batched-pipeline observability (ISSUE 1): snapshot the cumulative
    # batch histograms + wakeup counters at the end of every Sink stream.
    # Printed BEFORE the final yield so the line is flushed before the
    # client unblocks on the stream reply; the client picks the snapshot
    # matching its last timed round by ordinal.
    try:
        import json as _json

        from tpurpc.utils import stats as _st
        print("BATCHSTATS", _json.dumps({"batch": _st.batch_snapshot(),
                                         "counters": _st.counters_snapshot()}),
              flush=True)
    except Exception:
        pass
    yield {"bytes": np.int64(total), "check": np.float64(float(checksum))}

add_tensor_method(srv, "Sink", consume, kind="stream_stream")
add_tensor_method(srv_probe, "Sink", consume, kind="stream_stream")

# ---- serving flagship (BASELINE configs #4/#5): ResNet + fan-in batching --
# Full ResNet-50 @224 on an accelerator; the thin-18 @64 stand-in on the CPU
# fallback so the smoke stays fast. fixed_bucket -> ONE compiled shape.
batcher = None
if os.environ.get("TPURPC_BENCH_SERVING", "1") == "1":
    import jax.numpy as jnp
    from tpurpc.models.resnet import (init_resnet, make_infer_fn,
                                      resnet18_thin, resnet50)

    on_accel = dev.platform not in ("cpu",)
    if on_accel:
        model, img, model_name = resnet50(dtype=jnp.bfloat16), 224, "resnet50"
    else:
        # Stand-in geometry (TPURPC_BENCH_SERVING_IMG): the CPU fallback
        # phase exists to exercise the SERVING TRANSPORT, so the stand-in
        # must leave the transport as the bottleneck. At @64 a 1-core rig
        # is compute-bound before depth 1 even saturates (measured: the
        # idle-core ceiling for thin-18@64 is ~1.6K inf/s, which depth-1
        # serving already half-fills) and the ISSUE 3 depth sweep would
        # measure conv throughput, not pipelining. @48 keeps thin-18
        # recognizable while restoring transport-boundedness; artifacts
        # record the geometry (serving_image_size) so rounds compare
        # like-for-like (r2-r5 ran @64).
        img = int(os.environ.get("TPURPC_BENCH_SERVING_IMG", "48"))
        model, model_name = resnet18_thin(), "resnet18_thin"
    variables = init_resnet(jax.random.PRNGKey(0), model, image_size=img)
    infer = jax.jit(make_infer_fn(model))
    MAXB = int(os.environ.get("TPURPC_BENCH_SERVING_BATCH", "8"))

    def serve_fn(tree):
        return {"logits": infer(variables, tree["x"])}

    # NOTE on depth-aware flush: serve_jax wires FanInBatcher to
    # Server.inflight_requests (flush as soon as no more arrivals can
    # come). The BENCH batcher deliberately stays on timer/size-only
    # batching: under fixed_bucket (every dispatch padded+compiled at
    # max_batch) a flush heuristic misfiring in the closed-loop stagger
    # gap costs 7/8 of the compute, and cross-round serving_qps
    # comparability (r2-r5 artifacts) rides this exact configuration.
    batcher = FanInBatcher(serve_fn, max_batch=MAXB, max_delay_s=0.005,
                          fixed_bucket=True,
                          transfer_dtype=jnp.bfloat16 if on_accel else None)
    add_tensor_method(srv_infer, "Infer", batcher)
    # warm the single compiled batch shape before READY
    warm = np.zeros((MAXB, img, img, 3), np.float32)
    jax.tree_util.tree_map(lambda x: x.block_until_ready(),
                           infer(variables, warm))
    # Analytic per-inference FLOPs straight from XLA's cost model (exact for
    # the compiled graph; no hand-derived constant to go stale), and a
    # device-only batched-inference rate: MFU of the *compute path* with the
    # RPC/tunnel out of the picture. Serving QPS divided by the same peak
    # gives end-to-end MFU; the gap between the two is transport cost.
    flops_per_inf = 0.0
    dev_qps = 0.0
    try:
        ca = (jax.jit(make_infer_fn(model))
              .lower(variables, warm).compile().cost_analysis())
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops_per_inf = float(ca.get("flops", 0.0)) / MAXB
        warm_dev = jax.device_put(warm)  # exclude h2d from the compute rate
        reps, t0 = 0, time.time()
        while time.time() - t0 < 1.0:
            jax.tree_util.tree_map(lambda x: x.block_until_ready(),
                                   infer(variables, warm_dev))
            reps += 1
        dev_qps = reps * MAXB / (time.time() - t0)
    except Exception as exc:  # cost model is auxiliary: report, don't fail
        print("MFUERR", repr(exc), file=sys.stderr, flush=True)
    print("FLOPS", flops_per_inf, round(dev_qps, 1), flush=True)
    # stdout: the client parses this line (single source of model/img truth)
    print("SERVING", model_name, img, flush=True)

srv.start()
srv_infer.start()
srv_probe.start()
print("DEVKIND", getattr(dev, "device_kind", dev.platform), flush=True)
print("READY", dev.platform, ("serving" if batcher else "noserving"),
      flush=True)
srv.wait_for_termination(timeout=1200)
srv_infer.stop(grace=0)
srv_probe.stop(grace=0)
"""


class _ServerProc:
    """Bench server subprocess with line-oriented readiness + stderr capture."""

    def __init__(self, env):
        self.stderr_file = tempfile.NamedTemporaryFile(
            mode="w+", prefix="tpurpc_bench_srv_", suffix=".err", delete=False)
        self.proc = subprocess.Popen(
            [sys.executable, "-u", "-c", _SERVER_CODE],
            stdout=subprocess.PIPE, stderr=self.stderr_file, env=env,
            text=True)
        self._lines: list[str] = []
        self._cond = threading.Condition()
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self):
        for line in self.proc.stdout:
            with self._cond:
                self._lines.append(line.strip())
                self._cond.notify_all()
        with self._cond:
            self._lines.append(None)  # EOF sentinel
            self._cond.notify_all()

    def wait_line(self, prefix: str, timeout: float):
        deadline = time.time() + timeout
        seen = 0
        with self._cond:
            while True:
                while seen < len(self._lines):
                    line = self._lines[seen]
                    seen += 1
                    if line is None:
                        raise RuntimeError(
                            f"server exited before '{prefix}'"
                            f" (rc={self.proc.poll()})\n{self.stderr_tail()}")
                    if line.startswith(prefix):
                        return line
                remain = deadline - time.time()
                if remain <= 0:
                    raise TimeoutError(
                        f"server did not print '{prefix}' within {timeout}s\n"
                        f"{self.stderr_tail()}")
                self._cond.wait(remain)

    def nth_line(self, prefix: str, n: int, timeout: float):
        """n-th (1-based) buffered line starting with ``prefix``, waiting up
        to ``timeout`` for it to arrive; on timeout/EOF falls back to the
        latest earlier match (or None). Unlike ``wait_line`` this never
        raises — it serves auxiliary observability, not readiness."""
        deadline = time.time() + timeout
        with self._cond:
            while True:
                matches = [ln for ln in self._lines
                           if ln is not None and ln.startswith(prefix)]
                if len(matches) >= n:
                    return matches[n - 1]
                eof = bool(self._lines) and self._lines[-1] is None
                remain = deadline - time.time()
                if eof or remain <= 0:
                    return matches[-1] if matches else None
                self._cond.wait(remain)

    def stderr_tail(self, n=4000) -> str:
        try:
            self.stderr_file.flush()
            with open(self.stderr_file.name) as f:
                data = f.read()
            return "--- server stderr tail ---\n" + data[-n:]
        except OSError:
            return "(server stderr unavailable)"

    def kill(self):
        self.proc.kill()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        # failure paths already surfaced stderr via stderr_tail()
        try:
            self.stderr_file.close()
            os.unlink(self.stderr_file.name)
        except OSError:
            pass


def _serving_phase(port: int, model: str, img: int, platform: str = "cpu",
                   depth: "int | None" = None):
    """8-client fan-in (BASELINE config #4): concurrent image requests over
    independent connections, batched server-side into one jitted call.
    Returns (qps, model_name, n_requests); raises on failure.

    ``depth`` pins the per-client in-flight window (the ISSUE 3 sweep:
    serving_qps_by_depth at 1/4/16); None keeps the platform default +
    TPURPC_BENCH_CLIENT_DEPTH override. At depth>1 the pure-Python channel
    now pipelines too (TensorClient.call_async — stream-id demux, no
    thread per call), so the sweep is meaningful with or without
    libtpurpc.so.

    Timing starts at a barrier AFTER every client has connected and warmed
    (connection setup + first-dispatch latency excluded from the steady-state
    figure the phase exists to measure)."""
    import threading

    import numpy as np

    from tpurpc.jaxshim import TensorClient
    from tpurpc.rpc.channel import Channel

    n_clients = int(os.environ.get("TPURPC_BENCH_SERVING_CLIENTS", "8"))
    per_client = int(os.environ.get("TPURPC_BENCH_SERVING_REQS", "16"))
    image = np.random.default_rng(0).standard_normal(
        (1, img, img, 3)).astype(np.float32)
    errors: list = []
    done = [0] * n_clients
    start = threading.Barrier(n_clients + 1)

    # Serving client discipline (round 5, interleaved same-weather A/B):
    # on the CPU fallback 8 BLOCKING clients on inline-read channels beat
    # 8 CQ-futures clients at depth 4 in 6 of 7 pairs, by 10-74% — the CQ
    # puller's wake chain costs more than pipelining recovers on one
    # shared core (the scalability profile's reader-thread result again).
    # On an ACCELERATOR the per-call latency (h2d over the tunnel)
    # dominates instead and pipelining is what keeps the batcher fed
    # (round 4's +36%), so the platform picks the default:
    # cpu -> depth 1 + inline; accelerator -> depth 4 + CQ.
    # TPURPC_BENCH_CLIENT_DEPTH overrides either way.
    default_depth = "1" if platform == "cpu" else "4"
    # a malformed override must FAIL (the phase reports it), not silently
    # benchmark the platform default as if the operator's depth ran
    depth_env = (int(os.environ.get("TPURPC_BENCH_CLIENT_DEPTH",
                                    default_depth))
                 if depth is None else int(depth))

    def _make_channel():
        # NativeChannel (ctypes over libtpurpc.so) when available: the
        # closed-loop client's per-call overhead is part of the measured
        # QPS, and the native loop is ~3x the pure-Python path
        # (BASELINE.md). TPURPC_BENCH_NATIVE_CLIENT=0 opts out.
        if os.environ.get("TPURPC_BENCH_NATIVE_CLIENT", "1") == "1":
            try:
                from tpurpc.rpc.native_client import NativeChannel

                # depth 1: inline-read (round 5's same-weather winner).
                # depth>1: reader+CQ — the ISSUE 3 cross-plane A/B (python
                # and native servers, img 32 and 48) measured CQ above the
                # inline worker window at every depth>1 cell (e.g. 1310 vs
                # 1093 qps at depth 16 on the native plane): depth threads
                # on one core cost more than the CQ puller's wake chain.
                return NativeChannel("127.0.0.1", port,
                                     inline_read=depth_env <= 1,
                                     pipeline_depth=max(1, depth_env))
            except Exception:
                pass  # lib missing/unbuildable: pure-Python path
        return Channel(f"127.0.0.1:{port}")

    # In-flight calls per client: >1 pipelines through the native CQ
    # futures path so the batcher sees clients*depth outstanding requests.
    # History: round 4 measured +36% at depth 4 over depth-1-with-reader;
    # round 5's wake-chain findings flipped it — depth 1 on INLINE-READ
    # channels (no reader, no CQ puller) wins by 10-29% same-weather, so
    # it is the default (the artifact's serving_client_depth records what
    # ran; r4 artifacts carry depth 4).
    depth = depth_env

    used_depth = [1] * n_clients  # what each client ACTUALLY ran
    #: channel discipline each client ACTUALLY got — depth-1 artifacts are
    #: only cross-round comparable within one mode (inline vs reader vs
    #: python differ 10-74%, the whole point of the round-5 default)
    used_mode = ["python"] * n_clients

    def client(idx: int):
        try:
            with _make_channel() as ch:
                from tpurpc.rpc.native_client import NativeChannel as _NC

                if isinstance(ch, _NC):
                    used_mode[idx] = ("native-inline" if ch.inline_read
                                      else "native-reader")
                cli = TensorClient(ch, depth=max(1, depth))
                cli.call("Infer", {"x": image}, timeout=300)  # per-conn warm
                futures_fn = None
                if depth > 1:
                    # Pipelined window, both planes (ISSUE 3): the native
                    # channel rides its CQ (reader mode) or bounded inline
                    # window; the Python channel rides PipelinedUnary —
                    # stream-id demux on the reader, no thread per call
                    # (the old .future thread-churn caveat no longer
                    # applies).
                    pl = cli.pipeline("Infer", depth=depth)
                    futures_fn = pl.call_async
                    used_depth[idx] = depth
                start.wait(timeout=600)
                if futures_fn is None:
                    for _ in range(per_client):
                        out = cli.call("Infer", {"x": image}, timeout=300)
                        assert np.asarray(out["logits"]).shape[0] == 1
                        done[idx] += 1
                else:
                    inflight = []
                    issued = 0
                    while issued < per_client or inflight:
                        while issued < per_client and len(inflight) < depth:
                            inflight.append(
                                futures_fn({"x": image}, timeout=300))
                            issued += 1
                        out = inflight.pop(0).result(timeout=300)
                        assert np.asarray(out["logits"]).shape[0] == 1
                        done[idx] += 1
        except Exception as exc:  # surfaced after join
            errors.append(exc)
            try:
                start.abort()  # never leave the main thread at the barrier
            except Exception:
                pass

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    [t.start() for t in threads]
    start.wait(timeout=600)
    t0 = time.perf_counter()
    [t.join(timeout=600) for t in threads]
    dt = time.perf_counter() - t0
    if errors:
        raise errors[0]
    if any(t.is_alive() for t in threads):
        raise TimeoutError("serving client thread still running after join "
                           "timeout; qps would be measured on a racing "
                           "partial count")
    total = sum(done)
    # one mode in practice (all clients build identically); report the set
    # defensively so a mixed run is visible rather than mislabeled
    modes = sorted(set(used_mode))
    return (total / dt, model, total, max(used_depth),
            modes[0] if len(modes) == 1 else ",".join(modes))


def _run_once(env, n_msgs: int, ready_s: float):
    import numpy as np

    # Round isolation for the client-side batch/wakeup counters (a fallback
    # rerun must not inherit the dead first attempt's histograms).
    try:
        from tpurpc.utils import stats as _st
        _st.reset_batch_stats()
    except Exception:
        pass

    srv = _ServerProc(env)
    try:
        port_line = srv.wait_line("PORT", 60).split()
        port = int(port_line[1])
        port_infer = int(port_line[2]) if len(port_line) > 2 else port
        port_probe = int(port_line[3]) if len(port_line) > 3 else port
        ready = srv.wait_line("READY", ready_s)
        parts = ready.split()
        platform = parts[1]
        serving_on = len(parts) > 2 and parts[2] == "serving"

        from tpurpc.jaxshim import TensorClient
        from tpurpc.rpc.channel import Channel

        payload = np.ones((1024, 1024), np.float32)  # 4 MiB
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            # The client side of the measured-best plane (see _SERVER_CODE's
            # sink comment): the bulk stream rides the PYTHON plane, whose
            # rendezvous path one-sided-writes every 4 MiB payload into the
            # server's pre-granted landing region (tpurpc-express, ISSUE 9;
            # 3.6 vs 1.72 GB/s same-weather). TPURPC_BENCH_SINK_NATIVE=1
            # opts back to the native framed loop.
            sink_native = os.environ.get("TPURPC_BENCH_SINK_NATIVE",
                                         "0") != "0"

            # warmup RPC: decode jit + ring bring-up out of the timing.
            # It also settles the descriptor-ring adoption handshake
            # (tpurpc-pulse): steady state must show ZERO control frames.
            list(cli.duplex("Sink", gen(2), native=sink_native, timeout=300))
            ctrl0 = _ctrl_counters()

            # Calibrate HERE — after the (possibly minutes-long) backend
            # bring-up, immediately before the timed rounds — so the
            # yardstick samples the same host weather as the measurement.
            calib = _calibration()

            # Load-aware repetition (VERDICT r3 weak #1: the shared 1-core
            # host's noisy neighbors made round-over-round deltas ±39%
            # measurement noise). More timed rounds, outlier rejection by
            # reporting the median of the FASTEST majority (trimming only
            # slow outliers — contamination on this host is always one-sided:
            # a neighbor stealing the core makes rounds slower, never
            # faster), plus best-round alongside for ceiling-spotting.
            try:
                rounds = max(1, int(os.environ.get("TPURPC_BENCH_ROUNDS",
                                                   "5")))
            except ValueError:
                rounds = 5
            dts = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                replies = list(cli.duplex("Sink", gen(n_msgs),
                                          native=sink_native, timeout=600))
                dt = time.perf_counter() - t0
                total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
                assert total == n_msgs * payload.nbytes, (total, n_msgs)
                dts.append(dt)
            dts.sort()
            # fastest ceil(n/2) rounds: 3 of 5 at the default — the slow
            # tail (the only direction contamination pushes) is dropped
            kept = dts[:max(1, (len(dts) + 1) // 2)]
            dt = kept[len(kept) // 2]  # median of kept
            globals()["_LAST_STREAM_DTS"] = dts  # full sorted detail for JSON
            ctrl1 = _ctrl_counters()  # client-side delta over the rounds

        # Batch-pipeline observability (ISSUE 1): the server prints one
        # cumulative BATCHSTATS snapshot per completed Sink stream —
        # warmup is match #1, the last timed round is match rounds+1.
        batch_stats: dict = {}
        nstats = rounds + 1
        if sink_native:
            # The timed rounds rode the native C plane, whose batching isn't
            # visible to the Python counters. One short UNTIMED pass on the
            # instrumented Python plane (after the measurement) fills the
            # histograms so the artifact can still attribute throughput to
            # batch sizes; it is labeled as a probe, never the measurement.
            try:
                with Channel(f"127.0.0.1:{port_probe}") as pch:
                    list(TensorClient(pch).duplex("Sink", gen(8),
                                                  native=False, timeout=300))
                nstats += 1
                batch_stats["probe"] = "python-plane, 8 msgs, untimed"
            except Exception:
                pass
        try:
            line = srv.nth_line("BATCHSTATS", nstats, 10)
            if line:
                batch_stats["server"] = json.loads(line.split(" ", 1)[1])
        except Exception:
            pass
        # tpurpc-pulse (ISSUE 13): control-plane cost as a TRACKED series.
        # Deltas over the timed rounds — client side from registry
        # snapshots bracketing the rounds, server side from the warmup vs
        # last-round BATCHSTATS ordinals — yield control frames, forced
        # consumer wakeups (kicks) and thread parks PER BULK MESSAGE.
        ctrl_plane = None
        try:
            srv_warm = srv_end = {}
            w = srv.nth_line("BATCHSTATS", 1, 10)
            if w:
                srv_warm = (json.loads(w.split(" ", 1)[1])
                            .get("counters") or {})
            if batch_stats.get("server"):
                srv_end = batch_stats["server"].get("counters") or {}
            msgs = rounds * n_msgs

            def delta(name):
                c = ctrl1.get(name, 0) - ctrl0.get(name, 0)
                s = srv_end.get(name, 0) - srv_warm.get(name, 0)
                return c + s

            frames = delta("rdv_ctrl_frames")
            kicks = delta("ctrl_ring_kicks")
            parks = delta("wait_sleep")
            ctrl_plane = {
                "msgs": msgs,
                "ctrl_frames": frames,
                "ctrl_kicks": kicks,
                "thread_parks": parks,
                "ring_posts": delta("ctrl_ring_posts"),
                "ring_records": delta("ctrl_ring_records"),
                "ring_full_fallbacks": delta("ctrl_ring_full_fallbacks"),
                # the headline: control frames + forced consumer wakeups
                # per bulk message (≈0 in descriptor-ring steady state)
                "ctrl_wakeups_per_msg": (round((frames + kicks) / msgs, 4)
                                         if msgs else None),
                "ctrl_parks_per_msg": (round(parks / msgs, 4)
                                       if msgs else None),
            }
        except Exception as exc:
            sys.stderr.write(f"ctrl-plane delta capture failed: {exc}\n")
        try:
            from tpurpc.utils import stats as _st
            batch_stats["client"] = {"batch": _st.batch_snapshot(),
                                     "counters": _st.counters_snapshot()}
        except Exception:
            pass

        # tpurpc-lens (ISSUE 8): per-hop byte-flow waterfall for the
        # streaming path — the instrument that names the 1.72→8.5 GB/s
        # bottleneck hop (ROADMAP item 2). Client-side hops come from this
        # process's lens counters; server-side hops are scraped over the
        # introspection plane from the sink that ran the INSTRUMENTED
        # python plane (the probe port when the measured sink was native —
        # labeled, exactly like the batch-stats probe above).
        waterfall = None
        try:
            import urllib.request

            from tpurpc.obs import lens as _lens

            wf_port = port_probe if sink_native else port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{wf_port}/debug/waterfall",
                    timeout=5) as resp:
                wf_server = json.loads(resp.read())
            wf_client = _lens.waterfall()
            waterfall = _merge_waterfalls([wf_client, wf_server])
            waterfall["plane"] = ("python-probe" if sink_native
                                  else "measured")
        except Exception as exc:
            sys.stderr.write(f"waterfall capture failed: {exc}\n")

        # tpurpc-express (ISSUE 9): the message-size sweep measuring the
        # rendezvous-vs-framed crossover (~20s; Python plane, fresh
        # channels; the main timed rounds above are untouched)
        size_sweep = None
        if os.environ.get("TPURPC_BENCH_SIZESWEEP", "1") == "1":
            try:
                size_sweep = _stream_by_size(port)
            except Exception as exc:
                sys.stderr.write(f"stream_by_size sweep failed: {exc}\n")

        serving = None
        extras = {"stream_dts": [round(x, 3) for x in
                                 globals().get("_LAST_STREAM_DTS", [])],
                  "calibration": calib,
                  "batch_stats": batch_stats,
                  "waterfall": waterfall,
                  "stream_by_size": size_sweep,
                  "ctrl_plane": ctrl_plane}
        try:
            extras["device_kind"] = srv.wait_line("DEVKIND", 5).split(
                " ", 1)[1].strip()
        except Exception:
            pass
        if serving_on:
            try:
                # the server's SERVING line (printed before READY) is the
                # single source of truth for the model/image geometry
                _, model, img = srv.wait_line("SERVING", 10).split()
                extras["serving_image_size"] = int(img)
                try:
                    _, flops, dev_qps = srv.wait_line("FLOPS", 5).split()
                    extras["model_flops_per_inference"] = float(flops)
                    extras["device_infer_qps"] = float(dev_qps)
                except Exception:
                    pass
                serving = _serving_phase(port_infer, model, int(img),
                                         platform=platform)
                # Depth sweep (ISSUE 3): the same phase pinned to in-flight
                # windows 1/4/16 — the artifact's serving_qps_by_depth
                # shows what client pipelining buys the batcher.
                sweep = {}
                for d in (1, 4, 16):
                    try:
                        sweep[str(d)] = round(_serving_phase(
                            port_infer, model, int(img), platform=platform,
                            depth=d)[0], 1)
                    except Exception as exc:
                        sys.stderr.write(
                            f"serving depth-{d} sweep failed: {exc}\n")
                extras["serving_qps_by_depth"] = sweep
            except Exception as exc:  # serving is auxiliary: report, don't fail
                sys.stderr.write(f"serving phase failed: {exc}\n")
        return total / dt / 1e9, platform, serving, extras
    except Exception:
        sys.stderr.write(srv.stderr_tail() + "\n")
        raise
    finally:
        srv.kill()


def _ctrl_counters() -> dict:
    """Client-side registry snapshot of the control-plane counters the
    ctrl_wakeups_per_msg series is computed from (tpurpc-pulse)."""
    try:
        from tpurpc.obs import metrics as _metrics

        reg = _metrics.registry().metrics()
        out = {}
        for name in ("rdv_ctrl_frames", "ctrl_ring_kicks",
                     "ctrl_ring_posts", "ctrl_ring_records",
                     "ctrl_ring_full_fallbacks"):
            m = reg.get(name)
            if m is not None:
                out[name] = m.snapshot()
        from tpurpc.utils import stats as _st

        out["wait_sleep"] = _st.counters_snapshot().get("wait_sleep", 0)
        return out
    except Exception:
        return {}


def _merge_waterfalls(docs: "list[dict]") -> dict:
    """Sum hop tables from several processes (client + server side of one
    stream): bytes and busy time add, effective GB/s recomputes over the
    sums — the same merge the shard fan-out applies."""
    merged: dict = {}
    order: list = []
    for doc in docs:
        for r in (doc or {}).get("hops", ()):
            hop = r.get("hop")
            if hop not in merged:
                merged[hop] = {"hop": hop, "bytes": 0, "busy_ms": 0.0,
                               "copy_bytes": 0}
                order.append(hop)
            merged[hop]["bytes"] += int(r.get("bytes") or 0)
            merged[hop]["busy_ms"] += float(r.get("busy_ms") or 0.0)
            merged[hop]["copy_bytes"] += int(r.get("copy_bytes") or 0)
    rows = []
    for hop in order:
        r = merged[hop]
        ns = r["busy_ms"] * 1e6
        r["gbps"] = round(r["bytes"] / ns, 3) if ns else 0.0
        r["busy_ms"] = round(r["busy_ms"], 3)
        rows.append(r)
    # the lens's bottleneck rule (incl. the control-only-traffic guard: a
    # hop carrying <1% of the bulk bytes cannot be the bulk bottleneck)
    from tpurpc.obs import lens as _lens

    return {"hops": rows, "slowest_hop": _lens.slowest_hop(rows)}


def _lens_overhead(duration: "float | None" = None, pairs: int = 2) -> dict:
    """tpurpc-lens overhead gate (ISSUE 8): the continuous stage-sampling
    profiler at its DEFAULT rate (~50 Hz walking every thread stack)
    versus the same closed loop with the sampler stopped.
    ``lens_overhead_pct`` carries the <3% acceptance gate. The waterfall
    hop counters are always-on in BOTH legs (they are plain registry
    counters, priced by the obs gate since ISSUE 4) — this gate isolates
    the one genuinely new continuous cost, the sampler thread. Same
    alternation and best-draw-p50 methodology as _obs_overhead."""
    import io

    from tpurpc.bench import micro
    from tpurpc.obs import profiler
    from tpurpc.utils import stats as _st

    if duration is None:
        duration = float(os.environ.get("TPURPC_BENCH_OBS_S", "1.0"))
    prev_fast = os.environ.get("TPURPC_NATIVE_FAST_UNARY")
    os.environ["TPURPC_NATIVE_FAST_UNARY"] = "0"
    prof = profiler.get()
    srv = micro.run_server(0, max_workers=8)
    target = f"127.0.0.1:{srv.bench_port}"
    devnull = io.StringIO()
    p50s = {"off": [], "on": []}

    def leg(key, dur):
        r = micro.run_client(target, req_size=64, duration=dur, out=devnull)
        p50s[key].append(r["rtt_us"]["p50"])

    try:
        micro.run_client(target, req_size=64, duration=0.3,
                         out=devnull)  # warm: connect + first-dispatch
        for i in range(max(1, pairs)):
            legs = [("off", False), ("on", True)]
            if i % 2:
                legs.reverse()
            for key, on in legs:
                if on:
                    prof.start()
                else:
                    prof.stop()
                leg(key, duration)
    finally:
        prof.stop()  # later benches decide their own profiling
        if prev_fast is None:
            os.environ.pop("TPURPC_NATIVE_FAST_UNARY", None)
        else:
            os.environ["TPURPC_NATIVE_FAST_UNARY"] = prev_fast
        srv.stop(grace=0)
        _st.reset_batch_stats()

    def pct(on_key, off_key):
        # best-draw p50s: contamination on a shared core is one-sided (see
        # _obs_overhead.pct)
        off = min(p50s[off_key])
        on = min(p50s[on_key])
        return round((on - off) / off * 100, 2) if off else 0.0

    gate = pct("on", "off")
    return {
        "lens_overhead_pct": gate,
        "lens_overhead_gate_pct": 3.0,
        "lens_overhead_pass": gate < 3.0,
        "lens_hz": prof.hz,
        "lens_p50_us": {k: [round(x, 1) for x in sorted(v)]
                        for k, v in p50s.items()},
    }


def _obs_overhead(duration: "float | None" = None, pairs: int = 3) -> dict:
    """tpurpc-scope overhead gate (ISSUE 4): micro closed-loop RPC rate
    with telemetry FULLY ENABLED vs the default-off state, on the
    INSTRUMENTED Python plane (TPURPC_NATIVE_FAST_UNARY=0 for the gate's
    duration — letting the untraced leg ride the native C loop would
    measure the plane gap, not the telemetry).

    "Fully enabled" = every registry counter/histogram/fleet gauge live
    (they always are — unconditional and branch-free), a scraper thread
    rendering the Prometheus text at 4 Hz (~60x a production cadence)
    DURING the traffic, and tracing ACTIVE at the production sampling
    rate (TPURPC_BENCH_OBS_RATE, default 0.05 — 12x Dapper's default).
    ``obs_overhead_pct`` (positive = telemetry cost) carries the <3%
    gate. ``obs_traced100_pct`` is the informational cost of tracing
    EVERY call (a debugging mode, not an operating point): ~7 span
    records per 64-byte no-op RPC is measurable by construction.

    ON/OFF legs alternate and medians compare, so noisy-neighbor weather
    hits both sides alike."""
    import io
    import threading

    from tpurpc.bench import micro
    from tpurpc.obs import scrape, tracing
    from tpurpc.utils import stats as _st

    if duration is None:
        duration = float(os.environ.get("TPURPC_BENCH_OBS_S", "1.0"))
    rate = float(os.environ.get("TPURPC_BENCH_OBS_RATE", "0.05"))
    prev_fast = os.environ.get("TPURPC_NATIVE_FAST_UNARY")
    os.environ["TPURPC_NATIVE_FAST_UNARY"] = "0"
    srv = micro.run_server(0, max_workers=8)
    target = f"127.0.0.1:{srv.bench_port}"
    devnull = io.StringIO()
    rates = {"off": [], "on": [], "traced100": []}
    p50s = {"off": [], "on": [], "traced100": []}

    def leg(key, dur):
        stop = threading.Event()
        t = None
        if key != "off":
            def scraper():
                while not stop.is_set():
                    scrape.render_prometheus()
                    stop.wait(0.25)

            t = threading.Thread(target=scraper, daemon=True)
            t.start()
        try:
            r = micro.run_client(target, req_size=64, duration=dur,
                                 out=devnull)
            rates[key].append(r["rate_rps"])
            p50s[key].append(r["rtt_us"]["p50"])
        finally:
            stop.set()
            if t is not None:
                t.join(timeout=2)

    try:
        micro.run_client(target, req_size=64, duration=0.3,
                         out=devnull)  # warm: connect + first-dispatch
        for i in range(max(1, pairs)):
            # Alternate leg ORDER per pair: on a noisy shared core the
            # host drifts over the gate's window, and a fixed off-then-on
            # order would alias that drift into the overhead number. The
            # pairwise differencing below cancels what alternation leaves.
            tracing.force(None)
            legs = [("off", 0.0), ("on", rate)]
            if i % 2:
                legs.reverse()
            for key, r in legs:
                tracing.configure(r)
                leg(key, duration)
            tracing.force(True)  # debugging mode: every call traced
            leg("traced100", duration / 2)
            tracing.force(None)
    finally:
        tracing.force(None)
        tracing.configure(0.0)
        if prev_fast is None:
            os.environ.pop("TPURPC_NATIVE_FAST_UNARY", None)
        else:
            os.environ["TPURPC_NATIVE_FAST_UNARY"] = prev_fast
        srv.stop(grace=0)
        _st.reset_batch_stats()  # the gate's traffic must not pollute
        tracing.reset()          # the artifact's own counters/spans

    def pct(key):
        """Best-draw p50 RTT comparison. Contamination on this shared
        1-core host is ONE-SIDED (a noisy neighbor only ever slows a leg
        — the same argument behind the streaming phase's kept-fastest
        rounds and the calibration's best-of-5), so the minimum p50 of
        each config approximates its uncontended cost and the delta is
        the telemetry's own price, not the weather's."""
        off = min(p50s["off"])
        on = min(p50s[key])
        return round((on - off) / off * 100, 2) if off else 0.0

    gate = pct("on")
    return {
        "obs_overhead_pct": gate,
        "obs_overhead_gate_pct": 3.0,
        "obs_overhead_pass": gate < 3.0,
        "obs_sample_rate": rate,
        "obs_traced100_pct": pct("traced100"),
        "obs_p50_us": {k: [round(x, 1) for x in sorted(v)]
                       for k, v in p50s.items()},
        "obs_rps": {k: [round(x) for x in sorted(v)]
                    for k, v in rates.items()},
    }


def _flight_overhead(duration: "float | None" = None, pairs: int = 2) -> dict:
    """tpurpc-blackbox overhead gate (ISSUE 5): the ALWAYS-ON postmortem
    core — flight recorder emitting + stall-watchdog per-RPC registration
    and background sweeps — versus the same loop with both suppressed.
    ``flight_overhead_pct`` carries the <3% acceptance gate. By design the
    recorder emits on state EDGES only (a healthy closed loop produces
    near-zero events), so the measured cost is the watchdog's dict
    store/delete per RPC plus the suppressed-emit branch.

    ``tail_capture_pct`` is the INFORMATIONAL cost of tail-based trace
    capture (every RPC gets a provisional span buffer; spans are recorded
    and then dropped for healthy calls) — it is a separately-toggleable
    feature (TPURPC_TRACE_TAIL=0) and is reported, not gated: its price is
    the same ballpark as obs_traced100_pct, paid to guarantee a span tree
    for every pathological call at sample rate 0.

    Tail capture is held in its default-ON state for BOTH flight legs so
    the flight delta isolates the recorder+watchdog; the tail legs then
    toggle only tail capture with recorder+watchdog on. Same alternation
    and best-draw-p50 methodology as _obs_overhead."""
    import io

    from tpurpc.bench import micro
    from tpurpc.obs import flight, tracing, watchdog
    from tpurpc.utils import stats as _st

    if duration is None:
        duration = float(os.environ.get("TPURPC_BENCH_OBS_S", "1.0"))
    prev_fast = os.environ.get("TPURPC_NATIVE_FAST_UNARY")
    os.environ["TPURPC_NATIVE_FAST_UNARY"] = "0"
    srv = micro.run_server(0, max_workers=8)
    target = f"127.0.0.1:{srv.bench_port}"
    devnull = io.StringIO()
    p50s = {"off": [], "on": [], "tail_off": [], "tail_on": []}
    wd = watchdog.get()

    def leg(key, dur):
        r = micro.run_client(target, req_size=64, duration=dur, out=devnull)
        p50s[key].append(r["rtt_us"]["p50"])

    try:
        tracing.force(None)
        tracing.configure(0.0)
        micro.run_client(target, req_size=64, duration=0.3,
                         out=devnull)  # warm: connect + first-dispatch
        for i in range(max(1, pairs)):
            legs = [("off", False), ("on", True)]
            if i % 2:
                legs.reverse()
            for key, enabled in legs:
                flight.RECORDER.enabled = enabled
                wd.enabled = enabled
                leg(key, duration)
            # tail capture A/B (informational): recorder+watchdog stay on
            tail_legs = [("tail_off", False), ("tail_on", None)]
            if i % 2:
                tail_legs.reverse()
            for key, mode in tail_legs:
                tracing.tail(mode)
                leg(key, duration / 2)
    finally:
        flight.RECORDER.enabled = True
        wd.enabled = True
        wd.reset()
        tracing.tail(None)
        tracing.force(None)
        tracing.configure(0.0)
        if prev_fast is None:
            os.environ.pop("TPURPC_NATIVE_FAST_UNARY", None)
        else:
            os.environ["TPURPC_NATIVE_FAST_UNARY"] = prev_fast
        srv.stop(grace=0)
        _st.reset_batch_stats()
        tracing.reset()

    def pct(on_key, off_key):
        # best-draw p50s: contamination on a shared core is one-sided (see
        # _obs_overhead.pct) — the minimum of each leg approximates its
        # uncontended cost
        off = min(p50s[off_key])
        on = min(p50s[on_key])
        return round((on - off) / off * 100, 2) if off else 0.0

    gate = pct("on", "off")
    return {
        "flight_overhead_pct": gate,
        "flight_overhead_gate_pct": 3.0,
        "flight_overhead_pass": gate < 3.0,
        "tail_capture_pct": pct("tail_on", "tail_off"),
        "flight_p50_us": {k: [round(x, 1) for x in sorted(v)]
                          for k, v in p50s.items()},
    }


def _proto_verify_overhead(duration: "float | None" = None,
                           pairs: int = 4) -> dict:
    """tpurpc-proof overhead gate (ISSUE 12): the LIVE protocol verifier
    (``TPURPC_VERIFY_PROTOCOL=1`` — every flight event checked against
    the declared machines as it is recorded) versus the same loop with no
    verifier installed. ``proto_verify_overhead_pct`` carries the <3%
    acceptance gate. By design the cost rides the flight recorder's
    edges-not-traffic economy: a healthy closed loop emits near-zero
    events, so the verifier's per-event machine step is almost never
    taken — the measured cost is one global load + None check per emit.
    Same alternation and best-draw-p50 methodology as _obs_overhead."""
    import io

    from tpurpc.analysis import protocol
    from tpurpc.bench import micro
    from tpurpc.utils import stats as _st

    if duration is None:
        duration = float(os.environ.get("TPURPC_BENCH_OBS_S", "1.0"))
    prev_fast = os.environ.get("TPURPC_NATIVE_FAST_UNARY")
    os.environ["TPURPC_NATIVE_FAST_UNARY"] = "0"
    srv = micro.run_server(0, max_workers=8)
    target = f"127.0.0.1:{srv.bench_port}"
    devnull = io.StringIO()
    p50s = {"off": [], "on": []}
    verifier = None

    def leg(key, dur):
        r = micro.run_client(target, req_size=64, duration=dur, out=devnull)
        p50s[key].append(r["rtt_us"]["p50"])

    try:
        micro.run_client(target, req_size=64, duration=0.3,
                         out=devnull)  # warm: connect + first-dispatch
        for i in range(max(1, pairs)):
            legs = [("off", False), ("on", True)]
            if i % 2:
                legs.reverse()
            for key, enabled in legs:
                if enabled:
                    verifier = protocol.install_live()
                else:
                    protocol.uninstall_live()
                leg(key, duration)
    finally:
        protocol.uninstall_live()
        if prev_fast is None:
            os.environ.pop("TPURPC_NATIVE_FAST_UNARY", None)
        else:
            os.environ["TPURPC_NATIVE_FAST_UNARY"] = prev_fast
        srv.stop(grace=0)
        _st.reset_batch_stats()

    off = min(p50s["off"])
    on = min(p50s["on"])
    gate = round((on - off) / off * 100, 2) if off else 0.0
    return {
        "proto_verify_overhead_pct": gate,
        "proto_verify_overhead_gate_pct": 3.0,
        "proto_verify_overhead_pass": gate < 3.0,
        "proto_verify_events_checked": (verifier.checked if verifier
                                        else 0),
        "proto_verify_violations": (len(verifier.violations) if verifier
                                    else 0),
        "proto_verify_p50_us": {k: [round(x, 1) for x in sorted(v)]
                                for k, v in p50s.items()},
    }


def _argus_overhead(duration: "float | None" = None, pairs: int = 3) -> dict:
    """tpurpc-argus overhead gate (ISSUE 14): the whole detect loop armed
    — tsdb sampler on a 4 Hz grain (4x the production 1 s default), the
    SLO evaluator ticking at 4 Hz over a declared (never-firing)
    objective, and a fleet collector polling the serving port's /metrics
    + /debug/slo + /debug/flight + /traces at 4 Hz over real HTTP —
    versus the same closed loop with all three stopped.
    ``argus_overhead_pct`` carries the <3% acceptance gate;
    ``tsdb_resident_bytes`` records the history plane's bounded memory
    (informational — fixed by construction: preallocated rings x series
    cap). Same alternation and best-draw-p50 methodology as
    _obs_overhead: the sampler/evaluator/collector are background
    cadences, so their cost shows up as closed-loop RTT contention."""
    import io

    from tpurpc.bench import micro
    from tpurpc.obs import slo as _slo
    from tpurpc.obs import tsdb as _tsdb
    from tpurpc.obs.collector import FleetCollector
    from tpurpc.utils import stats as _st

    if duration is None:
        duration = float(os.environ.get("TPURPC_BENCH_OBS_S", "1.0"))
    prev_fast = os.environ.get("TPURPC_NATIVE_FAST_UNARY")
    os.environ["TPURPC_NATIVE_FAST_UNARY"] = "0"
    srv = micro.run_server(0, max_workers=8)
    target = f"127.0.0.1:{srv.bench_port}"
    devnull = io.StringIO()
    p50s = {"off": [], "on": []}

    # the armed plane: 4 Hz sampler over the REAL registry, an evaluator
    # with an objective that never fires (no trip/page noise in the timed
    # window), a collector process-alike polling over loopback HTTP
    db = _tsdb.Tsdb(fine_s=0.25)
    ev = _slo.SloEvaluator(eval_s=0.25, tsdb=db)
    ev.declare(_slo.SloObjective(
        "bench-guard", latency_ms=60_000.0, target_pct=50.0,
        windows=[(2.0, 8.0, 1e9)]))
    col = FleetCollector([target], poll_s=0.25)

    def leg(key, dur):
        if key == "on":
            db.start()
            ev.start()
            col.start()
        try:
            r = micro.run_client(target, req_size=64, duration=dur,
                                 out=devnull)
            p50s[key].append(r["rtt_us"]["p50"])
        finally:
            if key == "on":
                col.stop()
                ev.stop()
                db.stop()

    try:
        micro.run_client(target, req_size=64, duration=0.3,
                         out=devnull)  # warm: connect + first-dispatch
        for i in range(max(1, pairs)):
            legs = ["off", "on"]
            if i % 2:
                legs.reverse()
            for key in legs:
                leg(key, duration)
    finally:
        col.stop()
        ev.stop()
        db.stop()
        if prev_fast is None:
            os.environ.pop("TPURPC_NATIVE_FAST_UNARY", None)
        else:
            os.environ["TPURPC_NATIVE_FAST_UNARY"] = prev_fast
        srv.stop(grace=0)
        _st.reset_batch_stats()

    off = min(p50s["off"])
    on = min(p50s["on"])
    gate = round((on - off) / off * 100, 2) if off else 0.0
    return {
        "argus_overhead_pct": gate,
        "argus_overhead_gate_pct": 3.0,
        "argus_overhead_pass": gate < 3.0,
        "argus_sampler_hz": 4.0,
        "tsdb_resident_bytes": db.resident_bytes(),
        "tsdb_series": len(db.series()),
        "argus_p50_us": {k: [round(x, 1) for x in sorted(v)]
                         for k, v in p50s.items()},
    }


def _diagnose_overhead(duration: "float | None" = None,
                       pairs: int = 3) -> dict:
    """tpurpc-oracle overhead gate (ISSUE 20): the causal diagnosis
    engine armed — a tsdb sampler feeding the fine windows at 4 Hz plus
    a background querier running the FULL ``diagnose_doc`` pipeline
    (symptom scan, change-point detection over every series, all rules'
    collect+score, noisy-OR combination) at 4 Hz — versus the same
    closed loop with both stopped. ``diagnose_overhead_pct`` carries the
    <3% acceptance gate. The engine is pull-only (the `diag` lint rule
    enforces read-only evidence collection), so its cost is pure reader
    contention on the planes' locks — exactly what this gate prices.
    Same alternation and best-draw-p50 methodology as _obs_overhead."""
    import io
    import threading

    from tpurpc.bench import micro
    from tpurpc.obs import diagnose as _dz
    from tpurpc.obs import tsdb as _tsdb
    from tpurpc.utils import stats as _st

    if duration is None:
        duration = float(os.environ.get("TPURPC_BENCH_OBS_S", "1.0"))
    prev_fast = os.environ.get("TPURPC_NATIVE_FAST_UNARY")
    os.environ["TPURPC_NATIVE_FAST_UNARY"] = "0"
    srv = micro.run_server(0, max_workers=8)
    target = f"127.0.0.1:{srv.bench_port}"
    devnull = io.StringIO()
    p50s = {"off": [], "on": []}
    runs = {"n": 0}

    db = _tsdb.Tsdb(fine_s=0.25)
    stop_ev = threading.Event()
    worker = {"t": None}

    def query_loop():
        while not stop_ev.wait(0.25):
            try:
                _dz.diagnose_doc({})
                runs["n"] += 1
            except Exception:
                pass

    def leg(key, dur):
        if key == "on":
            db.start()
            stop_ev.clear()
            worker["t"] = threading.Thread(target=query_loop, daemon=True)
            worker["t"].start()
        try:
            r = micro.run_client(target, req_size=64, duration=dur,
                                 out=devnull)
            p50s[key].append(r["rtt_us"]["p50"])
        finally:
            if key == "on":
                stop_ev.set()
                if worker["t"] is not None:
                    worker["t"].join(timeout=2.0)
                db.stop()

    try:
        micro.run_client(target, req_size=64, duration=0.3,
                         out=devnull)  # warm: connect + first-dispatch
        for i in range(max(1, pairs)):
            legs = ["off", "on"]
            if i % 2:
                legs.reverse()
            for key in legs:
                leg(key, duration)
    finally:
        stop_ev.set()
        db.stop()
        if prev_fast is None:
            os.environ.pop("TPURPC_NATIVE_FAST_UNARY", None)
        else:
            os.environ["TPURPC_NATIVE_FAST_UNARY"] = prev_fast
        srv.stop(grace=0)
        _st.reset_batch_stats()

    off = min(p50s["off"])
    on = min(p50s["on"])
    gate = round((on - off) / off * 100, 2) if off else 0.0
    return {
        "diagnose_overhead_pct": gate,
        "diagnose_overhead_gate_pct": 3.0,
        "diagnose_overhead_pass": gate < 3.0,
        "diagnose_queries_run": runs["n"],
        "diagnose_p50_us": {k: [round(x, 1) for x in sorted(v)]
                            for k, v in p50s.items()},
    }


def _fleet_bench() -> dict:
    """tpurpc-fleet benches (ISSUE 6), in-process, seconds each:

    * ``fleet_qps`` — 3-server aggregate behind ``round_robin`` with a
      depth-8 pipelined client (the N-backend serving posture);
    * ``fleet_p99_degraded_pct`` — p99 latency with ONE slow replica,
      hedging on vs. off. The acceptance claim: hedging improves the
      degraded p99 ≥ 2x while total attempt amplification stays under the
      hedging policy's bound (no retry storm) — tail latency under
      contention is what the RPC layer owes the fleet (arXiv:1804.01138);
    * ``shed_curve`` — goodput/shed/p99 vs. offered load on an
      admission-gated server, plus the same worst offered load UNGATED:
      the gate trips before collapse (accepted-call p99 holds while the
      ungated leg queues).

    All servers run the Python plane (``native_dataplane=False``) and
    clients pin ``tpurpc_native=False`` — the features under test (load
    reports, hedging, admission) live there."""
    import threading

    from tpurpc.rpc.channel import Channel, HedgingPolicy
    from tpurpc.rpc.server import (AdmissionGate, Server,
                                   unary_unary_rpc_method_handler)

    def spawn(n, delay_of=None, max_workers=32, admission=None):
        rigs = []
        for i in range(n):
            srv = Server(max_workers=max_workers, admission=admission,
                         native_dataplane=False)
            calls = [0]
            d = delay_of(i) if delay_of else 0.0

            def handler(req, ctx, _c=calls, _d=d):
                _c[0] += 1
                if _d:
                    time.sleep(_d)
                return req

            srv.add_method("/fb.S/Echo",
                           unary_unary_rpc_method_handler(handler))
            port = srv.add_insecure_port("127.0.0.1:0")
            srv.start()
            rigs.append((srv, port, calls))
        return rigs

    def stop_all(rigs):
        for srv, _, _ in rigs:
            srv.stop(grace=0)

    out: dict = {}

    # -- fleet_qps: 3-server aggregate --------------------------------------
    rigs = spawn(3)
    try:
        addrs = ",".join(f"127.0.0.1:{p}" for _, p, _ in rigs)
        with Channel(f"ipv4:{addrs}", lb_policy="round_robin") as ch:
            pipe = ch.unary_unary("/fb.S/Echo",
                                  tpurpc_native=False).pipeline(depth=8)
            t_end = time.monotonic() + 0.3  # warm
            while time.monotonic() < t_end:
                pipe.call_async(b"w", timeout=10).result(10)
            n = 0
            t0 = time.monotonic()
            futs = []
            while time.monotonic() - t0 < 2.0:
                futs.append(pipe.call_async(b"x", timeout=10))
                if len(futs) >= 64:
                    for f in futs:
                        f.result(10)
                        n += 1
                    futs = []
            for f in futs:
                f.result(10)
                n += 1
            dt = time.monotonic() - t0
            pipe.close()
        out["fleet_qps"] = round(n / dt, 1)
        out["fleet_servers"] = 3
        per_server = [c[0] for _, _, c in rigs]
        out["fleet_qps_spread"] = per_server
    finally:
        stop_all(rigs)

    # -- fleet_p99_degraded_pct: one slow replica, hedging on vs off --------
    SLOW_S = 0.04
    N_CALLS = 120
    hp = HedgingPolicy(max_attempts=3, hedging_delay=0.008)
    rigs = spawn(3, delay_of=lambda i: SLOW_S if i == 0 else 0.0)
    try:
        addrs = ",".join(f"127.0.0.1:{p}" for _, p, _ in rigs)

        def leg(hedging):
            with Channel(f"ipv4:{addrs}", lb_policy="round_robin",
                         hedging_policy=hedging) as ch:
                mc = ch.unary_unary("/fb.S/Echo", tpurpc_native=False)
                for _ in range(6):
                    mc(b"w", timeout=10)  # warm every subchannel
                before = sum(c[0] for _, _, c in rigs)
                lats = []
                for _ in range(N_CALLS):
                    t0 = time.perf_counter()
                    mc(b"x", timeout=10)
                    lats.append((time.perf_counter() - t0) * 1000)
                time.sleep(SLOW_S + 0.05)  # cancelled losers finish counting
                attempts = sum(c[0] for _, _, c in rigs) - before
            lats.sort()
            return lats[max(0, int(len(lats) * 0.99) - 1)], attempts

        p99_off, attempts_off = leg(None)
        p99_on, attempts_on = leg(hp)
        out["fleet_p99_degraded_pct"] = {
            "slow_replica_s": SLOW_S,
            "calls": N_CALLS,
            "p99_ms_hedging_off": round(p99_off, 2),
            "p99_ms_hedging_on": round(p99_on, 2),
            "improvement_x": round(p99_off / p99_on, 2) if p99_on else None,
            "attempts_off": attempts_off,
            "attempts_on": attempts_on,
            # amplification must stay under the policy's hard bound — the
            # no-retry-storm half of the acceptance criterion
            "attempt_amplification": round(attempts_on / N_CALLS, 3),
            "amplification_bound": hp.max_attempts,
        }
    finally:
        stop_all(rigs)

    # -- shed_curve: goodput vs offered load through the admission gate -----
    HANDLER_S = 0.004
    gate = AdmissionGate(8, soft_limit=6)
    rigs = spawn(1, delay_of=lambda i: HANDLER_S, max_workers=8,
                 admission=gate)
    try:
        _, port, _ = rigs[0]

        def offered_leg(depth, target_port, leg_s=1.0):
            """One pipelined client whose WINDOW is the offered
            concurrency — a single issuing thread, so the 1-core host's
            client-side scheduling noise doesn't masquerade as server
            collapse (32 closed-loop threads measured the scheduler, not
            the gate)."""
            ok = [0]
            shed = [0]
            lat_ok: list = []
            lk = threading.Lock()
            with Channel(f"127.0.0.1:{target_port}") as ch:
                pipe = ch.unary_unary("/fb.S/Echo",
                                      tpurpc_native=False).pipeline(
                                          depth=depth)
                stop_at = time.monotonic() + leg_s
                t0 = time.monotonic()

                def issue():
                    t_req = time.perf_counter()
                    fut = pipe.call_async(b"x", timeout=10)

                    def done(f):
                        if f.exception() is None:
                            ok[0] += 1
                            with lk:
                                lat_ok.append(
                                    (time.perf_counter() - t_req) * 1000)
                        else:
                            shed[0] += 1

                    fut.add_done_callback(done)
                    return fut

                pending = []
                while time.monotonic() < stop_at:
                    pending.append(issue())
                    if len(pending) >= depth * 2:
                        for f in pending:
                            try:
                                f.result(10)
                            except Exception:
                                pass
                        pending = []
                for f in pending:
                    try:
                        f.result(10)
                    except Exception:
                        pass
                dt = time.monotonic() - t0
                pipe.close()
            lat_ok.sort()
            p99 = (lat_ok[max(0, int(len(lat_ok) * 0.99) - 1)]
                   if lat_ok else None)
            return {"offered_depth": depth,
                    "goodput_qps": round(ok[0] / dt, 1),
                    "shed_per_s": round(shed[0] / dt, 1),
                    "p99_ok_ms": round(p99, 2) if p99 else None}

        curve = [offered_leg(n, port) for n in (4, 8, 16, 32)]
        out["shed_curve"] = curve
        out["shed_rejected_total"] = gate.rejected
        # the ungated comparison at the worst offered load: same handler,
        # no gate — queueing latency the gate exists to cut off
        ungated = spawn(1, delay_of=lambda i: HANDLER_S, max_workers=8)
        try:
            out["shed_nogate_worst"] = offered_leg(32, ungated[0][1])
        finally:
            stop_all(ungated)
        goodputs = [c["goodput_qps"] for c in curve]
        peak = max(goodputs)
        out["shed_curve_noncollapse"] = round(
            min(goodputs[goodputs.index(peak):]) / peak, 3) if peak else None
    finally:
        stop_all(rigs)
    return out


#: tpurpc-manycore (ISSUE 7) — the sharded serving rig. The model is a
#: NUMPY matmul stand-in built pre-fork (plain arrays are fork-safe,
#: copy-on-write; an XLA client is not — that is why shard workers stay
#: jax-free here, and the artifact names the stand-in). Workers are full
#: per-core servers: own poller, rings (auto-scaled per shard), pool.
_SHARD_SERVER_CODE = r"""
import os, sys
import numpy as np
from tpurpc.jaxshim.service import add_tensor_method
from tpurpc.rpc.server import Server
from tpurpc.rpc.shard import ShardedServer

IMG = int(os.environ.get("TPURPC_BENCH_CORES_IMG", "48"))
WORKERS = int(sys.argv[1])

rng = np.random.default_rng(0)
W1 = rng.standard_normal((IMG * IMG * 3, 128)).astype(np.float32) * 0.01
W2 = rng.standard_normal((128, 10)).astype(np.float32) * 0.1

def model(tree):
    x = np.asarray(tree["x"], dtype=np.float32)
    x = x.reshape(x.shape[0], -1)
    return {"logits": np.maximum(x @ W1, 0.0) @ W2}

def build(shard_id):
    srv = Server(max_workers=32)
    add_tensor_method(srv, "Infer", model)
    return srv

sup = ShardedServer(build, workers=WORKERS, listener="reuseport").start()
print("PORT", sup.port, flush=True)
print("READY", flush=True)
sys.stdin.readline()
sup.stop()
"""

#: closed-loop client PROCESS (not thread): on a multi-core rig the load
#: generators must scale past the GIL too, or the sweep measures the
#: client's one core instead of the server's N.
_SHARD_CLIENT_CODE = r"""
import sys, time
import numpy as np
from tpurpc.jaxshim import TensorClient
from tpurpc.rpc.channel import Channel

port, depth, dur, img = (int(sys.argv[1]), int(sys.argv[2]),
                         float(sys.argv[3]), int(sys.argv[4]))
image = np.random.default_rng(0).standard_normal(
    (1, img, img, 3)).astype(np.float32)
with Channel(f"127.0.0.1:{port}") as ch:
    cli = TensorClient(ch, depth=max(1, depth))
    out = cli.call("Infer", {"x": image}, timeout=120)  # warm this conn
    assert np.asarray(out["logits"]).shape[0] == 1
    print("READY", flush=True)
    sys.stdin.readline()  # GO
    n = 0
    end = time.perf_counter() + dur
    if depth <= 1:
        while time.perf_counter() < end:
            cli.call("Infer", {"x": image}, timeout=120)
            n += 1
    else:
        pl = cli.pipeline("Infer", depth=depth)
        inflight = []
        while time.perf_counter() < end:
            while len(inflight) < depth:
                inflight.append(pl.call_async({"x": image}, timeout=120))
            inflight.pop(0).result(timeout=120)
            n += 1
        for f in inflight:
            f.result(timeout=120)
            n += 1
    print("DONE", n, flush=True)
"""


def _shard_cell(env, workers: int, n_clients: int, depth: int,
                duration_s: float, img: int) -> float:
    """One sweep cell: a sharded server subprocess + ``n_clients`` client
    processes released on a barrier; returns aggregate QPS."""
    srv = subprocess.Popen(
        [sys.executable, "-u", "-c", _SHARD_SERVER_CODE, str(workers)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=env, text=True)
    clients = []
    try:
        port = None
        deadline = time.time() + 60
        while time.time() < deadline:
            line = srv.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"shard server died: {srv.stderr.read()[-800:]}")
            if line.startswith("PORT"):
                port = int(line.split()[1])
            if line.startswith("READY"):
                break
        if port is None:
            raise TimeoutError("shard server never reported PORT")
        clients = [subprocess.Popen(
            [sys.executable, "-u", "-c", _SHARD_CLIENT_CODE, str(port),
             str(depth), str(duration_s), str(img)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True)
            for _ in range(n_clients)]
        for c in clients:
            line = c.stdout.readline()
            if not line.startswith("READY"):
                raise RuntimeError(
                    f"shard client died: {c.stderr.read()[-800:]}")
        t0 = time.perf_counter()
        for c in clients:  # the GO barrier: one newline each
            c.stdin.write("\n")
            c.stdin.flush()
        total = 0
        for c in clients:
            line = c.stdout.readline()
            if not line.startswith("DONE"):
                raise RuntimeError(
                    f"shard client failed: {c.stderr.read()[-800:]}")
            total += int(line.split()[1])
        dt = time.perf_counter() - t0
        return total / dt
    finally:
        for c in clients:
            c.kill()
        try:
            srv.stdin.write("\n")
            srv.stdin.flush()
            srv.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            srv.kill()


def _shard_bench() -> dict:
    """tpurpc-manycore (ISSUE 7): ``serving_qps_by_cores`` — aggregate QPS
    vs. worker count (1/2/4 per-core shard processes behind one
    SO_REUSEPORT port), plus the PR 3 depth sweep re-run WITH sharding.

    Methodology notes the artifact must carry:

    * ``cores_requested`` vs ``cores_achieved`` per cell, exactly like
      PR 3's concurrency probes — on a 1-core rig every worker count
      timeshares one core, so the sweep is expected ~flat there and the
      ≥2.5x@4 acceptance gate only APPLIES where ``cores_achieved >= 4``;
    * clients are PROCESSES (closed-loop, depth-4, barrier-released), so
      on a multi-core rig the load generation scales past the GIL too;
    * the model is a numpy matmul stand-in built pre-fork (fork-safe,
      jax-free workers) — this measures the SERVING PATH's core scaling,
      which is the thing sharding changes.
    """
    cpus = _cores_available()
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env.pop("PALLAS_AXON_POOL_IPS", None)  # jax-free, but belt+braces
    img = int(os.environ.get("TPURPC_BENCH_CORES_IMG", "48"))
    dur = float(os.environ.get("TPURPC_BENCH_CORES_S", "2.5"))
    n_clients = int(os.environ.get("TPURPC_BENCH_CORES_CLIENTS", "4"))
    out: dict = {}
    by_cores = {}
    achieved = {}
    for workers in (1, 2, 4):
        qps = _shard_cell(env, workers, n_clients, depth=4,
                          duration_s=dur, img=img)
        by_cores[str(workers)] = round(qps, 1)
        achieved[str(workers)] = min(workers, cpus)
    out["serving_qps_by_cores"] = by_cores
    out["serving_by_cores_requested"] = [1, 2, 4]
    out["serving_by_cores_achieved"] = achieved
    out["serving_by_cores_clients"] = n_clients
    out["serving_by_cores_model"] = (
        f"numpy relu-matmul stand-in @{img} (jax-free shard workers; "
        "fork-safe)")
    ratio = (by_cores["4"] / by_cores["1"]) if by_cores["1"] else 0.0
    out["serving_by_cores_scaling_x4"] = round(ratio, 2)
    # the acceptance gate (≥2.5x at 4 workers) binds on multi-core rigs;
    # elsewhere the honest record is requested-vs-achieved + a note
    out["serving_by_cores_gate"] = {
        "target_x": 2.5,
        "applicable": cpus >= 4,
        "pass": (ratio >= 2.5) if cpus >= 4 else None,
    }
    if cpus < 4:
        out["serving_by_cores_note"] = (
            f"{cpus}-core rig: all worker counts timeshare "
            f"{cpus} core(s), so the sweep is ~flat by physics (same "
            "regime as the PR 3 depth sweep); the machinery is validated "
            "here, the scaling claim binds on a multi-core rig — "
            "cores_achieved records the truth per cell")
    # the PR 3 depth sweep, re-run with sharding enabled: once the serving
    # core has headroom (multi-core rigs), depth should stop being flat
    sharded_workers = min(4, max(2, cpus))
    sweep = {}
    for depth in (1, 4, 16):
        qps = _shard_cell(env, sharded_workers, n_clients, depth=depth,
                          duration_s=dur, img=img)
        sweep[str(depth)] = round(qps, 1)
    out["serving_qps_by_depth_sharded"] = sweep
    out["serving_by_depth_sharded_workers"] = sharded_workers
    return out


def _gen_bench() -> dict:
    """tpurpc-cadence benches (ISSUE 10), in-process, ~15s total:

    * ``gen_tokens_per_s`` — aggregate decode goodput under a mixed
      interactive/batch closed-loop client set (the continuous-batching
      serving posture: many concurrent per-token streams, one device
      batch);
    * ``gen_ttft_ms`` — time-to-first-token at light load (p50) and at
      the heaviest offered load (interactive p99): the number the SLO
      classes exist to protect;
    * ``gen_shed_curve`` — goodput / sheds / per-class TTFT vs offered
      load (concurrent streaming clients), with the graceful-degradation
      acceptance recorded on file: goodput past saturation holds >= 0.75
      of peak, the batch class sheds FIRST, and interactive TTFT p99 at
      the worst load stays bounded vs the light-load baseline.

    The model is the deterministic numpy toy with a 1 ms step stand-in
    (named in ``gen_model``): the bench measures the SCHEDULER + streaming
    transport — join/leave churn, per-token flushes, shed behavior — not
    model FLOPs, exactly like the fleet bench measures the RPC layer.

    1-core caveat (the PR 3/PR 6 lesson, again): every offered-load
    client is a closed-loop thread SHARING the serving core, so the
    heaviest legs measure client-side scheduling pressure as well as the
    server — the sweep stops at 24 clients and ``gen_note`` says so."""
    import threading

    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.obs import watchdog as _wd
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.status import RpcError, StatusCode
    from tpurpc.serving import GenerationClient, serve_generation

    STEP_S = 0.001
    MAX_TOKENS = 24

    def leg(n_clients: int, leg_s: float = 1.2) -> dict:
        """One offered-load cell: ``n_clients`` closed-loop streaming
        clients (alternating interactive/batch) against a FRESH server,
        so no EWMA/queue state leaks between cells."""
        model = ToyDecodeModel(step_delay_s=STEP_S)
        srv, port, sched = serve_generation(
            model, max_batch=8, max_waiting=8, batch_shed_depth=4)
        lock = threading.Lock()
        stats = {"tokens": 0, "streams": 0,
                 "sheds": {"interactive": 0, "batch": 0},
                 "ttft_ms": {"interactive": [], "batch": []}}
        stop_at = [0.0]
        # barrier-released start (the _shard_bench discipline): channel
        # dialing happens OUTSIDE the measured window, or the big legs pay
        # their ramp-up inside the goodput denominator
        start = threading.Barrier(n_clients + 1)

        def client(slo: str):
            with Channel(f"127.0.0.1:{port}") as ch:
                gen = GenerationClient(ch)
                list(gen.generate([1], max_tokens=1, timeout=20))  # dial
                start.wait(30)
                while time.monotonic() < stop_at[0]:
                    t0 = time.perf_counter()
                    try:
                        it = iter(gen.call([7, 7], max_tokens=MAX_TOKENS,
                                           slo=slo, timeout=20))
                        next(it)
                        ttft = (time.perf_counter() - t0) * 1000
                        n = 1 + sum(1 for _ in it)
                    except RpcError as exc:
                        if exc.code() is StatusCode.UNAVAILABLE:
                            with lock:
                                stats["sheds"][slo] += 1
                            # a well-behaved shed client honors pushback
                            md = dict(exc.trailing_metadata() or ())
                            pb = int(md.get("tpurpc-pushback-ms", 25))
                            time.sleep(min(pb, 200) / 1000)
                            continue
                        raise
                    with lock:
                        stats["tokens"] += n
                        stats["streams"] += 1
                        stats["ttft_ms"][slo].append(ttft)

        try:
            stop_at[0] = time.monotonic() + 3600  # armed after the barrier
            threads = [threading.Thread(
                target=client,
                args=("interactive" if i % 2 == 0 else "batch",))
                for i in range(n_clients)]
            for t in threads:
                t.start()
            start.wait(60)
            t0 = time.monotonic()
            stop_at[0] = t0 + leg_s
            for t in threads:
                t.join(leg_s + 30)
            dt = time.monotonic() - t0
        finally:
            srv.stop(grace=0)
            sched.close()

        def p(q, xs):
            if not xs:
                return None
            xs = sorted(xs)
            return round(xs[max(0, int(len(xs) * q) - 1)], 2)

        return {
            "offered_clients": n_clients,
            "goodput_tokens_per_s": round(stats["tokens"] / dt, 1),
            "streams_per_s": round(stats["streams"] / dt, 1),
            "shed_per_s_interactive": round(
                stats["sheds"]["interactive"] / dt, 1),
            "shed_per_s_batch": round(stats["sheds"]["batch"] / dt, 1),
            "ttft_p50_ms_interactive": p(0.5,
                                         stats["ttft_ms"]["interactive"]),
            "ttft_p99_ms_interactive": p(0.99,
                                         stats["ttft_ms"]["interactive"]),
            "ttft_p99_ms_batch": p(0.99, stats["ttft_ms"]["batch"]),
            "avg_step_batch": round(
                sched.tokens_out / max(1, sched.steps), 2),
        }

    out: dict = {}
    # the watchdog's default 1s bar reads a healthy-but-queued token
    # stream as a stall and logs a flight replay per trip MID-MEASUREMENT
    # — silence it for the bench window (the decode-step attribution has
    # its own smoke + tests)
    wd = _wd.get()
    wd_was = wd.enabled
    wd.enabled = False
    try:
        light = leg(2)
        curve = [light] + [leg(n) for n in (4, 8, 16, 24)]
    finally:
        wd.enabled = wd_was
    out["gen_shed_curve"] = curve
    out["gen_note"] = (
        "1-core rig: offered-load clients share the serving core, so the "
        "heaviest legs include client-side scheduling cost; see "
        "ARCHITECTURE.md §19")
    goodputs = [c["goodput_tokens_per_s"] for c in curve]
    peak = max(goodputs)
    out["gen_tokens_per_s"] = peak
    out["gen_model"] = (f"toy affine-hash decode, step stand-in "
                        f"{STEP_S * 1000:.0f}ms, {MAX_TOKENS} tokens/stream")
    worst = curve[-1]
    out["gen_ttft_ms"] = {
        "light_p50": light["ttft_p50_ms_interactive"],
        "light_p99": light["ttft_p99_ms_interactive"],
        "worst_load_interactive_p99": worst["ttft_p99_ms_interactive"],
        "worst_load_batch_p99": worst["ttft_p99_ms_batch"],
    }
    # graceful degradation, on file: goodput past the peak never collapses
    # below 0.75x peak...
    past_peak = goodputs[goodputs.index(peak):]
    out["gen_shed_noncollapse"] = round(min(past_peak) / peak, 3) \
        if peak else None
    # ...the batch class absorbs the shedding first...
    sheds_i = sum(c["shed_per_s_interactive"] for c in curve)
    sheds_b = sum(c["shed_per_s_batch"] for c in curve)
    out["gen_batch_sheds_first"] = bool(sheds_b > sheds_i)
    out["gen_sheds_per_s_by_class"] = {"interactive": round(sheds_i, 1),
                                       "batch": round(sheds_b, 1)}
    # ...and interactive TTFT at the worst load stays bounded (record the
    # ratio; the acceptance eyeball is "held while batch sheds first")
    if light["ttft_p99_ms_interactive"] and \
            worst["ttft_p99_ms_interactive"]:
        out["gen_ttft_inflation_x"] = round(
            worst["ttft_p99_ms_interactive"]
            / max(0.01, light["ttft_p99_ms_interactive"]), 2)
    return out


def _odyssey_overhead(pairs: int = 7, phase_s: float = 0.8) -> dict:
    """tpurpc-odyssey gate (ISSUE 15): journey tracing + per-sequence
    accounting ON (the default posture: ledger per sequence, per-token
    ITL at the stream edge, per-step cost shares, journey spans into the
    tail buffer) vs OFF (``odyssey.force(False)``).

    Methodology: ONE long-lived decode scheduler fed in-process by
    closed-loop submitters carrying trace contexts and account keys (the
    exact PR 10 gen-bench decode regime at full token rate), with the
    odyssey gate toggled between adjacent PHASES and the gate computed
    as the MEDIAN of paired adjacent-phase diffs. In-process rather than
    over RPC because the toggle changes ONLY decode-loop-side work —
    the transport face passes trace/account identically in both states
    — while end-to-end closed-loop legs on this shared 1-core box swing
    ±5% with host weather, drowning a ~1% signal (the RPC-path tokens/s
    trajectory still rides ``_gen_bench`` with odyssey at its default
    ON). ``odyssey_overhead_pct < 3%`` is the acceptance gate; the on
    phases also record ``gen_itl_p99_us`` (the first token-latency
    series in the perf trajectory) and per-account accounting totals."""
    import threading

    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.obs import odyssey as _ody
    from tpurpc.obs import tracing as _tracing
    from tpurpc.obs import watchdog as _wd
    from tpurpc.serving.scheduler import DecodeScheduler

    STEP_S = 0.001
    MAX_TOKENS = 24
    N_FEEDERS = 10
    ACCOUNTS = ("bench-acct-a", "bench-acct-b")

    model = ToyDecodeModel(step_delay_s=STEP_S)
    sched = DecodeScheduler(model, max_batch=8, max_waiting=32,
                            name="ody-bench")
    stop = [False]

    def feeder(i: int):
        while not stop[0]:
            ctx = _tracing.maybe_sample()  # the api face's trace source
            try:
                st = sched.submit([7, 7], max_tokens=MAX_TOKENS,
                                  trace=ctx, account=ACCOUNTS[i % 2])
            except Exception:
                time.sleep(0.005)
                continue
            try:
                for _ in st:
                    pass
            except Exception:
                pass

    wd = _wd.get()
    wd_was = wd.enabled
    wd.enabled = False
    deltas: list = []
    rates = {"off": [], "on": []}

    def phase(on: bool) -> float:
        _ody.force(on)
        n0 = sched.tokens_out
        t0 = time.monotonic()
        time.sleep(phase_s)
        dt = time.monotonic() - t0
        return (sched.tokens_out - n0) / dt

    try:
        threads = [threading.Thread(target=feeder, args=(i,), daemon=True)
                   for i in range(N_FEEDERS)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # ramp, untimed
        for i in range(max(2, pairs)):
            if i % 2 == 0:
                off = phase(False)
                on = phase(True)
            else:
                on = phase(True)
                off = phase(False)
            rates["off"].append(off)
            rates["on"].append(on)
            if off > 0:
                deltas.append((off - on) / off * 100)
    finally:
        stop[0] = True
        _ody.force(None)
        wd.enabled = wd_was
        sched.close()
        for t in threads:
            t.join(10)
    deltas.sort()
    gate = deltas[len(deltas) // 2] if deltas else 0.0
    out = {
        "odyssey_overhead_pct": round(gate, 2),
        "odyssey_tokens_per_s": {
            "off": round(sorted(rates["off"])[len(rates["off"]) // 2], 1),
            "on": round(sorted(rates["on"])[len(rates["on"]) // 2], 1)},
        "odyssey_overhead_note": (
            "median of paired adjacent on/off phase diffs on one live "
            "decode loop; per-hook microcost ~0.4us/token (one ITL list "
            "append; hist+roll flush in 64-token batches) + ~1.8us/step "
            "(cost shares) + ~11us/seq (ledger lifecycle)"),
    }
    # the first token-latency series on file: rolling p99 ITL from the
    # on legs (µs), plus what the accounting plane attributed per account
    itl = _ody.itl_p99_us("interactive")
    if itl is not None:
        out["gen_itl_p99_us"] = round(itl, 1)
    accts = _ody.accounts_snapshot()
    out["odyssey_accounts"] = {
        name: {"seqs": int(b["seqs"]), "tokens": int(b["tokens"]),
               "step_us": round(b["step_us"], 1)}
        for name, b in sorted(accts.items()) if name in ACCOUNTS}
    return out


def _disagg_bench() -> dict:
    """tpurpc-keystone benches (ISSUE 11), in-process, ~15s total:

    * ``disagg_tokens_per_s`` / ``disagg_ttft_ms_p50`` vs the colocated
      PR 10 baseline (``disagg_baseline_*``): the same step stand-in and
      client count, once through ``serve_generation`` (prefill+decode in
      one scheduler) and once split prefill-tier -> decode-tier with the
      KV shipped over block grants — the cost of disaggregation on this
      1-core rig is on file, not guessed;
    * ``disagg_migration_blackout_ms`` — a live stream is migrated
      between two decode servers mid-generation; blackout is the worst
      inter-token gap, reported against the median healthy gap;
    * ``disagg_prefix_sweep`` — repeated-prompt fractions 0 / 0.5 / 0.9:
      measured prefix-cache hit rate and mean KV bytes shipped per
      request (a hit ships exactly one 16 B entry).
    """
    import numpy as _np

    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.obs import watchdog as _wd
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import (DisaggClient, GenerationClient, migrate,
                                serve_decode, serve_generation,
                                serve_prefill)

    STEP_S = 0.001
    N_CLIENTS = 4
    TOKENS = 48
    PROMPT = [7] * 24

    def drive(make_gen, n_clients=N_CLIENTS, tokens=TOKENS) -> dict:
        lock = threading.Lock()
        stats = {"tokens": 0, "ttft": []}
        start = threading.Barrier(n_clients + 1)

        def client():
            gen = make_gen()
            start.wait(30)
            for _ in range(3):
                t0 = time.perf_counter()
                n = 0
                for _tok in gen(PROMPT, tokens):
                    if n == 0:
                        ttft = (time.perf_counter() - t0) * 1000
                    n += 1
                with lock:
                    stats["tokens"] += n
                    stats["ttft"].append(ttft)

        threads = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for t in threads:
            t.start()
        start.wait(60)
        t0 = time.monotonic()
        for t in threads:
            t.join(60)
        dt = time.monotonic() - t0
        ttfts = sorted(stats["ttft"])
        return {
            "tokens_per_s": round(stats["tokens"] / dt, 1),
            "ttft_ms_p50": round(ttfts[len(ttfts) // 2], 2)
            if ttfts else None,
        }

    out: dict = {}
    wd = _wd.get()
    wd_was = wd.enabled
    wd.enabled = False
    try:
        # -- colocated baseline (PR 10 posture) --------------------------
        srv, port, sched = serve_generation(
            ToyDecodeModel(step_delay_s=STEP_S), max_batch=8)
        chans = []
        try:
            def mk():
                ch = Channel(f"127.0.0.1:{port}")
                chans.append(ch)
                cli = GenerationClient(ch)
                return lambda p, n: cli.generate(p, max_tokens=n,
                                                 timeout=30)
            base = drive(mk)
        finally:
            for ch in chans:
                ch.close()
            srv.stop(grace=0)
            sched.close()
        out["disagg_baseline_tokens_per_s"] = base["tokens_per_s"]
        out["disagg_baseline_ttft_ms_p50"] = base["ttft_ms_p50"]

        # -- disaggregated: prefill tier -> decode tier ------------------
        d_srv, d_port, d_sched, d_state = serve_decode(
            ToyDecodeModel(step_delay_s=STEP_S), max_batch=8,
            kv_blocks=512, block_bytes=1024)
        d_ch = Channel(f"127.0.0.1:{d_port}")
        p_srv, p_port, p_state = serve_prefill(
            ToyDecodeModel(), d_ch, f"127.0.0.1:{d_port}")
        clis = []
        try:
            def mkd():
                ch = Channel(f"127.0.0.1:{p_port}")
                chans.append(ch)
                cli = DisaggClient(ch, f"127.0.0.1:{d_port}")
                clis.append(cli)
                return lambda p, n: cli.generate(p, max_tokens=n,
                                                 timeout=30)
            dis = drive(mkd)
            out["disagg_tokens_per_s"] = dis["tokens_per_s"]
            out["disagg_ttft_ms_p50"] = dis["ttft_ms_p50"]
            out["disagg_prefix_hits_under_load"] = \
                d_state.mgr.prefix_hits

            # -- prefix-cache hit-rate sweep -----------------------------
            sweep = []
            rng = _np.random.default_rng(11)
            for frac in (0.0, 0.5, 0.9):
                hits0 = d_state.mgr.prefix_hits
                ship0 = p_state.shipped_bytes
                reqs = 20
                cli = clis[0]
                hot = [3] * 64
                for i in range(reqs):
                    p = hot if rng.random() < frac else \
                        [int(x) for x in rng.integers(1, 250, 64)]
                    list(cli.generate(p, max_tokens=2, timeout=30))
                sweep.append({
                    "repeat_fraction": frac,
                    "hit_rate": round(
                        (d_state.mgr.prefix_hits - hits0) / reqs, 2),
                    "mean_ship_bytes": round(
                        (p_state.shipped_bytes - ship0) / reqs, 1),
                })
            out["disagg_prefix_sweep"] = sweep
        finally:
            for cli in clis:
                cli.close()
            for ch in chans:
                try:
                    ch.close()
                except Exception:
                    pass
            p_srv.stop(grace=0)
            p_state.close()
            d_srv.stop(grace=0)
            d_sched.close()
            d_state.close()
            d_state.mgr.close()
            d_ch.close()

        # -- migration blackout ------------------------------------------
        a_srv, a_port, a_sched, a_state = serve_decode(
            ToyDecodeModel(step_delay_s=STEP_S), name="migA",
            kv_blocks=256, block_bytes=1024)
        b_srv, b_port, b_sched, b_state = serve_decode(
            ToyDecodeModel(step_delay_s=STEP_S), name="migB",
            kv_blocks=256, block_bytes=1024)
        a_ch = Channel(f"127.0.0.1:{a_port}")
        mp_srv, mp_port, mp_state = serve_prefill(
            ToyDecodeModel(), a_ch, f"127.0.0.1:{a_port}")
        mp_ch = Channel(f"127.0.0.1:{mp_port}")
        b_ch = Channel(f"127.0.0.1:{b_port}")
        cli = DisaggClient(mp_ch, f"127.0.0.1:{a_port}")
        try:
            stamps: list = []

            def stream():
                for _ in cli.generate([5] * 8, max_tokens=400,
                                      timeout=60):
                    stamps.append(time.perf_counter())

            t = threading.Thread(target=stream)
            t.start()
            while a_sched.running_depth() == 0 and t.is_alive():
                time.sleep(0.005)
            time.sleep(0.05)
            migrate(a_state, b_ch, f"127.0.0.1:{b_port}")
            t.join(60)
            gaps = [(b - a) * 1000
                    for a, b in zip(stamps, stamps[1:])]
            if gaps:
                gaps_sorted = sorted(gaps)
                out["disagg_migration_blackout_ms"] = round(max(gaps), 2)
                out["disagg_migration_median_gap_ms"] = round(
                    gaps_sorted[len(gaps_sorted) // 2], 3)
                out["disagg_migration_tokens"] = len(stamps)
        finally:
            cli.close()
            mp_srv.stop(grace=0)
            mp_state.close()
            a_srv.stop(grace=0)
            b_srv.stop(grace=0)
            a_sched.close()
            b_sched.close()
            a_state.close()
            b_state.close()
            a_state.mgr.close()
            b_state.mgr.close()
            for ch in (mp_ch, a_ch, b_ch):
                ch.close()
    finally:
        wd.enabled = wd_was
    out["disagg_note"] = (
        "toy 1ms-step stand-in: the bench measures the handoff/"
        "re-attach/migration machinery, not model FLOPs. Even on this "
        "1-core rig disagg tokens/s beats colocated — prefill leaves "
        "the decode loop thread (colocated prefill stalls the step "
        "loop between boundaries) — while TTFT pays the extra "
        "prefill-hop round trip; real fleets also scale the tiers "
        "independently")
    return out


def _hive_bench() -> dict:
    """tpurpc-hive (ISSUE 16): connection-scale curves — live p99 and
    resident bytes per connection as the PARKED fleet ramps 1k → 10k →
    50k pairs (1% of each level stays active; a fixed 32-connection
    driver set is what's timed, so the curve isolates the cost of parked
    mass rather than traffic mix). Gates: p99 with the 50k-level fleet
    parked within 25% of the 100-connection baseline, and <= 4 KiB
    resident per parked pair (the ring + status regions must live in the
    shared RingPool, not the pair).

    Loopback connections cost ~10 fds each, so RLIMIT_NOFILE caps the
    achievable fleet on most rigs — every level records target vs
    achieved and the artifact says loudly when it was capped."""
    import resource

    import tpurpc.core.pair as _pair

    drivers_n = 32
    msg = b"\xa5" * 256
    _pair.RingPool.reset()
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            soft = hard
        except (ValueError, OSError):
            pass
    cap = max(drivers_n + 8, (soft - 200) // 10)

    def pump(a, b):
        for p in (a, b):
            try:
                if p.drain_notifications():
                    p.kick()
            except Exception:
                pass

    def park_all(conns):
        now = time.monotonic()
        for a, b in conns:
            a.maybe_park(now, 0.0)
            b.maybe_park(now, 0.0)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pending = [(a, b) for a, b in conns
                       if not (a._parked and b._parked)]
            if not pending:
                return
            now = time.monotonic()
            for a, b in pending:
                pump(a, b)
                if not a._parked:
                    a.maybe_park(now, 0.0)
                if not b._parked:
                    b.maybe_park(now, 0.0)

    def drive_p99(drivers, samples=1500):
        lats = []
        deadline = time.monotonic() + 8
        while len(lats) < samples and time.monotonic() < deadline:
            for a, b in drivers:
                t0 = time.perf_counter()
                sent = 0
                while sent < len(msg):
                    sent += b.send([msg[sent:]])
                    pump(a, b)
                got = 0
                while got < len(msg):
                    got += len(a.recv() or b"")
                    pump(a, b)
                lats.append(time.perf_counter() - t0)
        lats.sort()
        return lats[min(len(lats) - 1, int(len(lats) * 0.99))]

    fleet = []      # (a, b) conns beyond the driver set
    out = {"hive_fd_limit": soft, "hive_conn_cap": cap,
           "hive_levels": []}
    try:
        drivers = [_pair.create_loopback_pair(ring_size=4096)
                   for _ in range(drivers_n)]
        # 100-connection baseline: drivers + 68 idle live connections
        fleet = [_pair.create_loopback_pair(ring_size=4096)
                 for _ in range(100 - drivers_n)]
        drive_p99(drivers, samples=300)  # warmup: byte-code/alloc caches
        base_p99 = drive_p99(drivers)
        out["hive_baseline_conns"] = 100
        out["hive_baseline_p99_us"] = round(base_p99 * 1e6, 1)
        for target_pairs in (1000, 10_000, 50_000):
            want_conns = min(target_pairs // 2, cap)
            while len(fleet) + drivers_n < want_conns:
                fleet.append(_pair.create_loopback_pair(ring_size=4096))
            park_all(fleet)
            parked = [p for a, b in fleet for p in (a, b) if p._parked]
            resident = (max(p.resident_bytes_est() for p in parked)
                        if parked else 0)
            p99 = drive_p99(drivers)
            stats = _pair.RingPool.get().stats()
            level = {
                "target_pairs": target_pairs,
                "parked_pairs": len(parked),
                "fd_capped": want_conns < target_pairs // 2,
                "live_p99_us": round(p99 * 1e6, 1),
                "p99_vs_baseline_pct": round(100 * p99 / base_p99, 1),
                "resident_bytes_per_parked_pair": resident,
                "ring_pool_free_mib": round(stats["free_bytes"] / 2**20, 2),
            }
            out["hive_levels"].append(level)
        last = out["hive_levels"][-1]
        out["hive_p99_gate_pct"] = last["p99_vs_baseline_pct"]
        out["hive_p99_gate_ok"] = last["p99_vs_baseline_pct"] <= 125.0
        out["hive_resident_gate_ok"] = (
            last["resident_bytes_per_parked_pair"] <= 4096)
        if last["fd_capped"]:
            out["hive_note"] = (
                f"fd limit {soft} caps the fleet at {cap} connections "
                f"({2 * cap} pairs) — the 50k level measured the capped "
                f"fleet; the per-pair resident + p99 curves are the claim, "
                f"not the absolute count")
    finally:
        for a, b in drivers + fleet:
            try:
                a.destroy()
                b.destroy()
            except Exception:
                pass
        _pair.RingPool.reset()
    return out


_NATIVE_LEG_CODE = r"""
import json, statistics, sys, time

mode, msgs, rounds = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
from tpurpc.obs import native_obs
from tpurpc.rpc import native_client
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

kw = {} if mode.startswith("native") else {"native_dataplane": False}
srv = Server(max_workers=4, **kw)
def total(req_iter, ctx):
    n = 0
    for m in req_iter:
        n += len(m)
    yield str(n).encode()
srv.add_method("/natbench.S/Sink", stream_stream_rpc_method_handler(total))
port = srv.add_insecure_port("127.0.0.1:0")
srv.start()
payload = b"\xa5" * (4 << 20)
opts = {} if mode.startswith("native") else {"tpurpc_native": False}
with Channel(f"127.0.0.1:{port}") as ch:
    mc = ch.stream_stream("/natbench.S/Sink", **opts)
    # warmup settles the capability hello + standing grants — the first
    # big send legitimately races the hello and frames
    list(mc(iter([payload, payload]), timeout=60))
    c0 = native_client.rdv_counters() or {}
    o0 = native_obs.counters()
    gbps = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        out = list(mc(iter([payload] * msgs), timeout=300))
        dt = time.perf_counter() - t0
        assert out[-1] == str(msgs * len(payload)).encode(), out
        gbps.append(msgs * len(payload) / dt / 1e9)
    c1 = native_client.rdv_counters() or {}
    o1 = native_obs.counters()
srv.stop(grace=1)
delta = {k: c1.get(k, 0) - c0.get(k, 0) for k in c1}
print("RESULT " + json.dumps({
    "gbps": round(statistics.median(gbps), 3),
    "gbps_rounds": [round(g, 3) for g in sorted(gbps)],
    "counters_delta": delta,
    "obs_delta": {k: o1.get(k, 0) - o0.get(k, 0) for k in o1},
    "total_msgs": rounds * msgs,
}), flush=True)
"""


def _native_bench(env) -> dict:
    """tpurpc-ironclad (ISSUE 18): the native-plane A/B — ``stream_4MiB``
    over (a) native client+server with rendezvous (the default ladder),
    (b) native forced framed (size bar pushed above every payload — same
    code path, zero offers, the honest framed control leg), and (c) the
    Python plane with rendezvous (the PR 7 headline path) — same weather:
    one run, sequential legs bracketed by a fresh memcpy yardstick.

    Emits the native plane's ``ctrl_wakeups_per_msg`` (process-global C
    counters: forced consumer kicks + framed control ops per message,
    ≈0 in the ring-borne steady state) and ``native_stream_vs_memcpy_pct``
    with the ≥80% gate BINDING wherever the rig has ≥2 cores; the honest
    ``applicable: false`` + note survives only on true 1-core rigs, where
    sender memcpy and receiver deliver timeshare one hart. Each leg is a
    fresh subprocess so the env knobs and the process-global counters
    start clean.

    tpurpc-xray rides the same run: ``native_ctrl_wakeups_per_msg`` is
    derived from the scraped shm metrics table (one vocabulary with
    /metrics and the tsdb), and a fourth leg with ``TPURPC_NATIVE_OBS=0``
    prices the instrument itself — ``native_obs_overhead_pct`` with the
    <3% gate every other telemetry layer already answers to."""
    cpus = _cores_available()
    msgs = int(os.environ.get("TPURPC_BENCH_NATIVE_MSGS", "48"))
    rounds = int(os.environ.get("TPURPC_BENCH_NATIVE_ROUNDS", "5"))
    lenv = dict(env)
    lenv["GRPC_PLATFORM_TYPE"] = "RDMA_BPEV"  # ring platform: C adoption
    lenv["JAX_PLATFORMS"] = "cpu"  # jax-free legs; belt + braces
    lenv.pop("PALLAS_AXON_POOL_IPS", None)

    def leg(mode, extra=None):
        e = dict(lenv)
        if extra:
            e.update(extra)
        p = subprocess.run(
            [sys.executable, "-u", "-c", _NATIVE_LEG_CODE, mode,
             str(msgs), str(rounds)],
            env=e, capture_output=True, text=True, timeout=240)
        lines = [ln for ln in p.stdout.splitlines()
                 if ln.startswith("RESULT ")]
        if p.returncode != 0 or not lines:
            raise RuntimeError(
                f"native bench leg {mode} failed: {p.stderr[-800:]}")
        return json.loads(lines[0][len("RESULT "):])

    out: dict = {}
    yard = _calibration().get("memcpy_gbps_best")  # same-weather yardstick
    rdv = leg("native_rdv")
    framed = leg("native_framed",
                 {"TPURPC_RENDEZVOUS_MIN_KB": str(1 << 20)})
    py = leg("python_rdv")
    d = rdv["counters_delta"]
    n = rdv["total_msgs"]
    out["native_stream_4MiB_gbps"] = rdv["gbps"]
    out["native_framed_4MiB_gbps"] = framed["gbps"]
    out["python_rdv_4MiB_gbps"] = py["gbps"]
    if framed["gbps"]:
        out["native_rdv_vs_framed_x"] = round(rdv["gbps"] / framed["gbps"],
                                              2)
    if py["gbps"]:
        out["native_vs_python_x"] = round(rdv["gbps"] / py["gbps"], 2)
    # the control-plane claim, C-side: kicks + framed control ops per bulk
    # message across the native leg's timed window (client AND server —
    # the counters are process-global, so ≈0 is the stronger statement).
    # tpurpc-xray: derived from the SCRAPED obs table — the same slots
    # /metrics, the tsdb, and tools/top read — so the bench artifact and
    # the live scrape can never tell different stories; the PR 18 ledger
    # carries the number only when the plane is off.
    od = rdv.get("obs_delta") or {}
    src = od if od else d
    out["native_ctrl_wakeups_per_msg"] = round(
        (src.get("ctrl_kicks", 0) + src.get("ctrl_frames", 0)) / n, 4)
    out["native_ctrl_wakeups_source"] = ("obs_table" if od else
                                         "rdv_ledger")
    out["native_rdv_fallbacks"] = d.get("rdv_fallback", 0)
    out["native_host_copy_bytes_per_msg"] = round(
        d.get("host_copy_bytes", 0) / n, 1)
    if yard:
        out["native_memcpy_gbps"] = yard
        pct = round(100 * rdv["gbps"] / yard, 1)
        out["native_stream_vs_memcpy_pct"] = pct
        # the ISSUE 18 flip: the 80% gate BINDS wherever ≥2 cores let the
        # receiver's deliver run beside the sender's memcpy
        out["native_stream_vs_memcpy_gate"] = {
            "target_pct": 80.0,
            "applicable": cpus >= 2,
            "pass": (pct >= 80.0) if cpus >= 2 else None,
        }
        if cpus < 2:
            out["native_stream_vs_memcpy_note"] = (
                "1-core rig: sender memcpy and receiver deliver timeshare "
                "one hart, so the ceiling is 1/(t_memcpy + t_consume) "
                "regardless of control-plane cost; "
                "native_ctrl_wakeups_per_msg (≈0) and the rdv-vs-framed "
                "A/B carry the native-plane claim here")
    # tpurpc-xray (ISSUE 19): the observability plane's own price — the
    # SAME native+rdv leg with TPURPC_NATIVE_OBS=0 (the C side reads it
    # at first use, so a fresh subprocess is the honest off state; the
    # rdv_write timing bracket is behind enabled(), keeping the off leg
    # free of clock reads too). Best-draw comparison: contamination on a
    # shared rig is one-sided, so max-of-rounds approximates each leg's
    # uncontended throughput and the delta is the instrument's cost.
    obsoff = leg("native_rdv", {"TPURPC_NATIVE_OBS": "0"})
    out["native_obs_off_4MiB_gbps"] = obsoff["gbps"]
    best_on = max(rdv["gbps_rounds"] or [rdv["gbps"]])
    best_off = max(obsoff["gbps_rounds"] or [obsoff["gbps"]])
    if best_off:
        pct = round(100.0 * (best_off - best_on) / best_off, 2)
        out["native_obs_overhead_pct"] = pct
        out["native_obs_overhead_gate_pct"] = 3.0
        out["native_obs_overhead_pass"] = pct < 3.0
    if cpus >= 2:
        # delivery-shard A/B: decode/deliver off the receive hart is only
        # a win when there is a second hart to take it
        noshard = leg("native_rdv", {"TPURPC_NATIVE_DELIVERY": "0"})
        out["native_noshard_4MiB_gbps"] = noshard["gbps"]
        if noshard["gbps"]:
            out["native_delivery_shard_speedup_x"] = round(
                rdv["gbps"] / noshard["gbps"], 2)
    else:
        out["native_delivery_shard_note"] = (
            "1-core rig: the delivery shard is auto-off (a queue handoff "
            "to the only hart); its A/B binds on ≥2-core rigs and the "
            "≥2.5x@4-core serving gate lives in serving_by_cores_gate")
    out["native_bench_method"] = {
        "payload_mib": 4, "msgs_per_round": msgs, "rounds": rounds,
        "stat": "median of rounds", "handler": "bytes sink (jax-free)",
        "rounds_sorted": {"native_rdv": rdv["gbps_rounds"],
                          "native_framed": framed["gbps_rounds"],
                          "python_rdv": py["gbps_rounds"],
                          "native_obs_off": obsoff["gbps_rounds"]},
    }
    return out


def _stream_by_size(port: int) -> dict:
    """tpurpc-express (ISSUE 9): message-size sweep 64 KiB → 16 MiB on the
    Python plane, rendezvous ON vs OFF (the size bar pushed above every
    payload), recording GB/s per cell and the measured crossover — so the
    TPURPC_RENDEZVOUS_MIN_KB default is a number this artifact justifies,
    not a guess. Each leg is budgeted by bytes, keeps the whole sweep to
    ~20 s, and reuses one channel per mode so steady-state (standing
    landing regions pre-granted) is what's measured."""
    import numpy as np

    from tpurpc.jaxshim import TensorClient
    from tpurpc.rpc.channel import Channel

    sizes = [64 << 10, 256 << 10, 1 << 20, 4 << 20, 16 << 20]
    budget = 96 << 20  # bytes per cell
    out: dict = {"sizes_kib": [s >> 10 for s in sizes],
                 "rendezvous_gbps": [], "framed_gbps": []}
    saved = os.environ.get("TPURPC_RENDEZVOUS_MIN_KB")
    try:
        for mode in ("rendezvous", "framed"):
            if mode == "framed":
                # push the size bar above every payload: same code path,
                # zero offers — the honest framed control leg
                os.environ["TPURPC_RENDEZVOUS_MIN_KB"] = str(1 << 20)
            elif saved is not None:
                os.environ["TPURPC_RENDEZVOUS_MIN_KB"] = saved
            else:
                os.environ.pop("TPURPC_RENDEZVOUS_MIN_KB", None)
            with Channel(f"127.0.0.1:{port}") as ch:
                cli = TensorClient(ch)
                for size in sizes:
                    # 2-D: the Sink handler's checksum reads arr[0, 0]
                    payload = np.ones((size // 1024, 256), np.float32)
                    msgs = max(4, budget // payload.nbytes)

                    def gen(k, p=payload):
                        for _ in range(k):
                            yield {"x": p}

                    # warm: jit + (rendezvous mode) standing grants
                    list(cli.duplex("Sink", gen(2), native=False,
                                    timeout=120))
                    t0 = time.perf_counter()
                    replies = list(cli.duplex("Sink", gen(msgs),
                                              native=False, timeout=300))
                    dt = time.perf_counter() - t0
                    import numpy as _np

                    total = int(_np.asarray(
                        replies[-1]["bytes"]).ravel()[0])
                    assert total == msgs * payload.nbytes
                    out[f"{mode}_gbps"].append(round(total / dt / 1e9, 2))
    finally:
        if saved is not None:
            os.environ["TPURPC_RENDEZVOUS_MIN_KB"] = saved
        else:
            os.environ.pop("TPURPC_RENDEZVOUS_MIN_KB", None)
    crossover = None
    for size, r, f in zip(sizes, out["rendezvous_gbps"],
                          out["framed_gbps"]):
        if r > f:
            crossover = size
            break
    out["crossover_bytes"] = crossover
    out["note"] = ("crossover = smallest message size where the "
                   "rendezvous plane beats the framed path; the "
                   "TPURPC_RENDEZVOUS_MIN_KB default (256) should sit at "
                   "or below it")
    return out


def _cores_available() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _calibration() -> dict:
    """Tiny host-speed probes so round-over-round artifacts are comparable
    across noisy-neighbor weather (VERDICT r3 weak #1): a memcpy-bandwidth
    probe (the streaming path is memcpy-bound on the CPU fallback) and a
    single-thread matmul probe. Best-of-5 each — the best draw approximates
    the uncontended host; the best/mean ratio (≤1; «1 = contended) exposes
    contamination during the calibration itself."""
    import numpy as np

    out: dict = {}
    try:
        src = np.ones(32 * 1024 * 1024 // 8, np.float64)  # 32 MiB
        dst = np.empty_like(src)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.copyto(dst, src)
            ts.append(time.perf_counter() - t0)
        out["memcpy_gbps_best"] = round(src.nbytes / min(ts) / 1e9, 2)
        out["memcpy_best_over_mean"] = round(min(ts) / (sum(ts) / len(ts)), 3)
        a = np.ones((384, 384), np.float32)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            (a @ a).sum()
            ts.append(time.perf_counter() - t0)
        gflop = 2 * 384**3 / 1e9
        out["matmul_gflops_best"] = round(gflop / min(ts), 1)
    except Exception as exc:  # calibration is metadata, never a failure
        out["error"] = repr(exc)
    return out


def _probe_backend(budget_s: float = 150.0) -> bool:
    """Cheap accelerator liveness verdict BEFORE burning the full ready_s
    bring-up budget on a dead tunnel (VERDICT r4 weak #2: 300 s of a ~600 s
    driver window went to waiting out a tunnel the harvest log had just
    declared dead 62 probes running).

    Two tiers: (1) free — the harvest loop's log, if its last probe verdict
    is fresh (≤12 min, its own cycle is ~7-9.5 min); (2) bench/probe.py in
    a subprocess bounded by ``budget_s`` (the tunnel black-holes rather
    than errors, so the bound must be external — SIGALRM does not fire
    inside the C extension). The budget matches harvest.sh's 150 s bound
    for the SAME probe file: a tunnel alive enough to answer it gets the
    full ready_s bring-up; only a black-holed one is declared dead."""
    here = os.path.dirname(os.path.abspath(__file__))
    log = os.path.join(here, "bench", "results", "harvest.log")
    try:
        import re
        from datetime import datetime, timezone
        with open(log) as f:
            lines = [ln for ln in f if "probe ALIVE" in ln or "probe dead" in ln]
        if lines:
            m = re.match(r"\[(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2})Z\]",
                         lines[-1])
            if m:
                ts = datetime.strptime(m.group(1),
                                       "%Y-%m-%dT%H:%M:%S").replace(
                                           tzinfo=timezone.utc)
                age = (datetime.now(timezone.utc) - ts).total_seconds()
                if age <= 720:
                    verdict = "ALIVE" in lines[-1]
                    sys.stderr.write(
                        f"pre-probe: harvest log verdict "
                        f"{'alive' if verdict else 'dead'} ({age:.0f}s old)\n")
                    return verdict
    except OSError:
        pass
    probe_py = os.path.join(here, "bench", "probe.py")
    try:
        rc = subprocess.run([sys.executable, probe_py], timeout=budget_s,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL).returncode
    except subprocess.TimeoutExpired:
        rc = -1
    sys.stderr.write(f"pre-probe: subprocess verdict "
                     f"{'alive' if rc == 0 else 'dead'}\n")
    return rc == 0


def main() -> None:
    os.environ.setdefault("GRPC_PLATFORM_TYPE",
                          os.environ.get("TPURPC_BENCH_PLATFORM", "RDMA_BPEV"))
    os.environ.setdefault("GRPC_RDMA_RING_BUFFER_SIZE_KB", "32768")

    n_msgs = int(os.environ.get("TPURPC_BENCH_MSGS", "96"))
    # Budget for jax backend bring-up on the default platform. Sized so a dead
    # TPU tunnel (observed: jax.devices() on axon not returning in 580 s) still
    # leaves room for the CPU-fallback run inside a ~600 s driver timeout.
    ready_s = float(os.environ.get("TPURPC_BENCH_READY_S", "300"))

    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__)) +
                         os.pathsep + env.get("PYTHONPATH", ""))

    try:
        load_start = os.getloadavg()
    except OSError:
        load_start = None

    fallback = False
    fallback_reason = "accelerator bring-up failed; reran on cpu"
    # Pre-probe (free via a fresh harvest-log verdict; else ≤150 s, the
    # bound shared with harvest.sh's probe) instead of paying ready_s for
    # a dead tunnel; the reclaimed minutes buy more timed rounds (noise,
    # the actual r4 weakness).
    if (env.get("TPURPC_BENCH_CPU") != "1"
            and env.get("TPURPC_BENCH_PROBE", "1") == "1"
            and env.get("PALLAS_AXON_POOL_IPS")
            and not _probe_backend()):
        env["TPURPC_BENCH_CPU"] = "1"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        fallback = True
        fallback_reason = "pre-probe: accelerator tunnel dead; ran on cpu"
        # spend the saved budget on noise reduction (_run_once reads the
        # round count from this process's os.environ, not the server env)
        os.environ.setdefault("TPURPC_BENCH_ROUNDS", "9")
    try:
        gbps, platform, serving, extras = _run_once(env, n_msgs, ready_s)
    except (TimeoutError, RuntimeError) as exc:
        if env.get("TPURPC_BENCH_CPU") == "1":
            raise
        sys.stderr.write(f"default-platform bench failed ({exc});"
                         f" retrying with JAX_PLATFORMS=cpu\n")
        env["TPURPC_BENCH_CPU"] = "1"
        # The axon sitecustomize registers the tunnel plugin whenever this
        # var is set, and a black-holing tunnel hangs backend init even
        # under jax_platforms=cpu — the fallback must not touch it at all.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        fallback = True
        gbps, platform, serving, extras = _run_once(env, n_msgs, ready_s)

    out = {
        "metric": "stream_4MiB_tensors_to_jax_Array",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
        "jax_platform": platform,
    }
    # Host-weather provenance (VERDICT r3 next-round #5): 1/5/15-min load
    # at start AND end brackets the measurement window; the calibration
    # probes give a host-speed yardstick to normalize cross-round deltas.
    try:
        load_end = os.getloadavg()
    except OSError:
        load_end = None
    if load_start is not None:
        out["host_load"] = {"start": [round(x, 2) for x in load_start],
                            "end": [round(x, 2) for x in load_end]
                            if load_end else None}
    out["calibration"] = extras.get("calibration", {})
    # tpurpc-scope overhead gate (ISSUE 4): telemetry fully on vs off,
    # micro closed-loop, medians of alternated legs; <3% is the contract.
    if os.environ.get("TPURPC_BENCH_OBS", "1") == "1":
        try:
            out.update(_obs_overhead())
        except Exception as exc:  # the gate is auxiliary: report, don't fail
            sys.stderr.write(f"obs overhead gate failed: {exc}\n")
            out["obs_overhead_error"] = repr(exc)
        # tpurpc-blackbox flight-recorder gate (ISSUE 5): recorder+watchdog
        # always-on vs suppressed; <3% is the acceptance contract.
        try:
            out.update(_flight_overhead())
        except Exception as exc:
            sys.stderr.write(f"flight overhead gate failed: {exc}\n")
            out["flight_overhead_error"] = repr(exc)
        # tpurpc-lens (ISSUE 8): continuous stage profiler at default Hz
        # vs stopped; <3% is the acceptance contract.
        try:
            out.update(_lens_overhead())
        except Exception as exc:
            sys.stderr.write(f"lens overhead gate failed: {exc}\n")
            out["lens_overhead_error"] = repr(exc)
        # tpurpc-proof (ISSUE 12): live protocol verifier on vs off;
        # <3% is the acceptance contract (edges-not-traffic economy).
        try:
            out.update(_proto_verify_overhead())
        except Exception as exc:
            sys.stderr.write(f"proto verify overhead gate failed: {exc}\n")
            out["proto_verify_overhead_error"] = repr(exc)
        # tpurpc-argus (ISSUE 14): tsdb sampler + slo evaluator + a 4 Hz
        # collector polling the serving port, on vs off; <3% gate plus
        # the informational tsdb_resident_bytes bound.
        try:
            out.update(_argus_overhead())
        except Exception as exc:
            sys.stderr.write(f"argus overhead gate failed: {exc}\n")
            out["argus_overhead_error"] = repr(exc)
        # tpurpc-oracle (ISSUE 20): the full diagnosis pipeline querying
        # at 4 Hz (change-point scan + every rule) vs idle; <3% gate —
        # asking "why" must cost nothing measurable.
        try:
            out.update(_diagnose_overhead())
        except Exception as exc:
            sys.stderr.write(f"diagnose overhead gate failed: {exc}\n")
            out["diagnose_overhead_error"] = repr(exc)
    # tpurpc-fleet (ISSUE 6): fleet_qps / fleet_p99_degraded_pct (hedging
    # on-vs-off with one slow replica) / shed_curve (admission gate vs
    # offered load). In-process, ~10s total.
    if os.environ.get("TPURPC_BENCH_FLEET", "1") == "1":
        try:
            out.update(_fleet_bench())
        except Exception as exc:
            sys.stderr.write(f"fleet bench failed: {exc}\n")
            out["fleet_bench_error"] = repr(exc)
    # tpurpc-manycore (ISSUE 7): serving QPS vs. shard-worker count (1/2/4
    # per-core processes, one SO_REUSEPORT port) + the depth sweep re-run
    # under sharding; cores_requested/achieved recorded like PR 3's
    # concurrency probes. ~35s, jax-free subprocesses.
    if os.environ.get("TPURPC_BENCH_CORES", "1") == "1":
        try:
            out.update(_shard_bench())
        except Exception as exc:
            sys.stderr.write(f"shard bench failed: {exc}\n")
            out["shard_bench_error"] = repr(exc)
    # tpurpc-cadence (ISSUE 10): continuous-batching generation serving —
    # tokens/s + TTFT vs offered load, and the shed-curve saturation sweep
    # proving graceful degradation. In-process, ~15s, jax-free.
    if os.environ.get("TPURPC_BENCH_GEN", "1") == "1":
        try:
            out.update(_gen_bench())
        except Exception as exc:
            sys.stderr.write(f"gen bench failed: {exc}\n")
            out["gen_bench_error"] = repr(exc)
        # tpurpc-odyssey (ISSUE 15): journey tracing + per-sequence cost
        # accounting on vs off under the gen bench; <3% gate, plus the
        # first token-latency series (gen_itl_p99_us) and the
        # per-account accounting totals.
        try:
            out.update(_odyssey_overhead())
        except Exception as exc:
            sys.stderr.write(f"odyssey overhead gate failed: {exc}\n")
            out["odyssey_overhead_error"] = repr(exc)
    # tpurpc-keystone (ISSUE 11): disaggregated prefill/decode vs the
    # colocated baseline, migration blackout, prefix-cache hit sweep.
    # In-process, ~15s, jax-free.
    if os.environ.get("TPURPC_BENCH_DISAGG", "1") == "1":
        try:
            out.update(_disagg_bench())
        except Exception as exc:
            sys.stderr.write(f"disagg bench failed: {exc}\n")
            out["disagg_bench_error"] = repr(exc)
    # tpurpc-hive (ISSUE 16): the connection-scale plane — live p99 +
    # resident bytes/connection as the parked fleet ramps 1k → 10k → 50k
    # pairs (fd-budget capped, loudly). In-process, ~15s, jax-free.
    if os.environ.get("TPURPC_BENCH_HIVE", "1") == "1":
        try:
            out.update(_hive_bench())
        except Exception as exc:
            sys.stderr.write(f"hive bench failed: {exc}\n")
            out["hive_bench_error"] = repr(exc)
    # tpurpc-ironclad (ISSUE 18): the native-plane A/B — stream_4MiB over
    # native+rdv vs native-framed vs python+rdv, same weather, with the
    # native ctrl_wakeups_per_msg and the memcpy gate binding on ≥2 cores.
    if os.environ.get("TPURPC_BENCH_NATIVE", "1") == "1":
        try:
            out.update(_native_bench(env))
        except Exception as exc:
            sys.stderr.write(f"native bench failed: {exc}\n")
            out["native_bench_error"] = repr(exc)
    if fallback:
        # Loud, unmissable: this artifact measured the CPU fallback, not the
        # chip — the number is NOT comparable to an accelerator run (and the
        # serving model is the thin stand-in, named in serving_model below).
        out["fallback"] = True
        out["fallback_reason"] = fallback_reason
    if extras.get("stream_dts"):
        out["stream_round_secs"] = extras["stream_dts"]  # sorted; median used
    # tpurpc-express (ISSUE 9): the headline stream vs the SAME-WEATHER
    # memcpy yardstick (the acceptance ratio), plus the size sweep with the
    # measured rendezvous-vs-framed crossover
    yard = out.get("calibration", {}).get("memcpy_gbps_best")
    if yard:
        out["memcpy_gbps"] = yard  # the same-weather yardstick, tracked
        out["stream_4MiB_vs_memcpy_pct"] = round(100 * gbps / yard, 1)
    # tpurpc-pulse (ISSUE 13): control-plane cost per bulk message — the
    # ~0.6 ms/msg of wakeups ARCHITECTURE §18 described in prose is now a
    # tracked series.  ctrl_wakeups_per_msg = control frames + forced
    # consumer wakeups (kicks) per message, ≈0 with the descriptor-ring
    # plane in steady state; thread_parks carries the residual fd-level
    # parks (framed acks, poll-slice expiries) for context.
    cp = extras.get("ctrl_plane")
    if cp:
        out["ctrl_wakeups_per_msg"] = cp.get("ctrl_wakeups_per_msg")
        out["ctrl_parks_per_msg"] = cp.get("ctrl_parks_per_msg")
        out["ctrl_plane"] = cp
    if yard and _cores_available() < 2:
        # Gate context (PR 7 precedent): stream ≥ 80% of the burst-memcpy
        # yardstick requires the RECEIVER's per-message work (decode,
        # delivery, jax materialization) to run on a core the sender's
        # memcpy is not using.  On a 1-core rig both processes share the
        # hart, so the ceiling is 1/(t_memcpy + t_consume) regardless of
        # control-plane cost — the 80% gate binds on ≥2-core hosts; the
        # recorded pct and the A/B vs TPURPC_CTRL_RING=0 carry the
        # control-plane claim here.
        out["stream_vs_memcpy_applicable"] = False
        out["stream_vs_memcpy_note"] = (
            "1-core rig: sender memcpy and receiver decode/deliver share "
            "one hart; ctrl_wakeups_per_msg (≈0) and the ring-off A/B are "
            "the control-plane evidence")
    if extras.get("stream_by_size"):
        out["stream_by_size"] = extras["stream_by_size"]
        out["rendezvous_crossover_bytes"] = extras["stream_by_size"].get(
            "crossover_bytes")
    # tpurpc-lens (ISSUE 8): the streaming phase's per-hop waterfall — the
    # next PR finds ROADMAP item 2's bottleneck hop ON FILE here.
    if extras.get("waterfall"):
        wf = extras["waterfall"]
        out["waterfall_gbps_by_hop"] = {
            r["hop"]: r["gbps"] for r in wf["hops"]}
        out["waterfall_slowest_hop"] = wf.get("slowest_hop")
        out["waterfall_plane"] = wf.get("plane")
        out["waterfall_detail"] = wf["hops"]
    # Batched receive pipeline (ISSUE 1): messages moved per receive-drain
    # wakeup, and how often waiters were satisfied inside the busy window
    # vs parked on fds. The drain happens on whichever side RECEIVES the
    # bulk stream — the server for Sink — so prefer its histogram; the
    # client-side one covers the ack path. A zero-count histogram means the
    # measured plane was the native one (C-side batching, not instrumented
    # by the Python counters) — the field is still emitted so rounds are
    # comparable.
    bs = extras.get("batch_stats") or {}
    hist = {"count": 0, "mean": 0.0, "p50": 0, "p99": 0, "side": None}
    for side in ("server", "client"):
        h = ((bs.get(side) or {}).get("batch") or {}).get("ring_drain")
        if h and h.get("count"):
            hist = dict(h, side=side)
            break
    out["batch_msgs_per_wakeup"] = hist
    merged: dict = {}
    for side in ("server", "client"):
        for name, v in ((bs.get(side) or {}).get("counters") or {}).items():
            merged[name] = merged.get(name, 0) + v
    waits = (merged.get("wait_spin_hit", 0) + merged.get("wait_spin_miss", 0)
             + merged.get("wait_spin_skipped", 0))
    out["poller_spin_sleep"] = {
        "spin_hit": merged.get("wait_spin_hit", 0),
        "spin_miss": merged.get("wait_spin_miss", 0),
        "spin_skipped": merged.get("wait_spin_skipped", 0),
        "sleep": merged.get("wait_sleep", 0),
        # fraction of waits satisfied inside the busy window (hit / all
        # wait entries); None when nothing waited (pure native plane)
        "spin_ratio": (round(merged.get("wait_spin_hit", 0) / waits, 4)
                       if waits else None),
    }
    out["batch_stats"] = bs  # full per-side detail for round-over-round
    if serving is not None:
        # BASELINE configs #4/#5 (8-client fan-in batching into a ResNet
        # server); the reference publishes no figure, so no vs_baseline.
        qps, model, total, used_depth, used_mode = serving
        out["serving_qps"] = round(qps, 1)
        out["serving_model"] = model
        if extras.get("serving_image_size"):
            # stand-in geometry provenance: r2-r5 ran the thin-18 stand-in
            # @64 (compute-bound on 1-core rigs); r6+ runs @48 so the
            # serving phase measures the transport — compare like-for-like
            out["serving_image_size"] = extras["serving_image_size"]
        out["serving_requests"] = total
        # config provenance: the depth AND channel discipline the phase
        # ACTUALLY ran (depth-1 artifacts are only comparable within one
        # mode — native-inline vs native-reader vs python differ 10-74%);
        # r1-r2 ran depth-1 reader/python, r4 depth-4 CQ
        out["serving_client_depth"] = used_depth
        out["serving_client_mode"] = used_mode
        if extras.get("serving_qps_by_depth"):
            # in-flight-window sweep (ISSUE 3): same phase at depth 1/4/16
            out["serving_qps_by_depth"] = extras["serving_qps_by_depth"]
            if platform == "cpu":
                # Measured context the sweep MUST carry on this rig: with
                # client+server+model sharing ONE core, depth-1 already
                # runs the core at 0% idle (/proc/stat during steady
                # state), so pipelining has no idle latency to convert
                # into throughput and the sweep is expected ~flat. Depth
                # pays off where depth-1 leaves the serving core waiting —
                # the axon-tunnel accelerator rig (round 4: +36% at depth
                # 4) or any multi-core host. Without this note a flat
                # sweep reads as a pipelining bug; it is host physics.
                out["serving_depth_note"] = (
                    "1-core rig: depth-1 saturates the shared core "
                    "(0% idle measured) — sweep flat by physics, see "
                    "ARCHITECTURE.md §12")
        flops = extras.get("model_flops_per_inference")
        if flops:
            # MFU = achieved model FLOP/s ÷ chip peak. Two flavors:
            # serving_mfu has the whole RPC+tunnel pipeline in it;
            # device_mfu is the compute path alone (batched, weights+pixels
            # already in HBM) — the gap between them is transport cost.
            peak, peak_src = _peak_flops(platform,
                                         extras.get("device_kind", ""),
                                         extras.get("calibration", {}))
            if extras.get("device_kind"):
                out["device_kind"] = extras["device_kind"]
            out["model_flops_per_inference"] = flops
            # The denominator is NAMED (VERDICT r4 next #2): on the CPU
            # fallback it is the calibration's own measured matmul rate —
            # the honest "what fraction of this host's matmul throughput
            # does the serving path feed" — never a placeholder constant.
            out["peak_flops"] = peak
            out["peak_flops_source"] = peak_src
            out["serving_mfu"] = round(qps * flops / peak, 8) if peak else None
            dev_qps = extras.get("device_infer_qps")
            if dev_qps:
                out["device_infer_qps"] = dev_qps
                out["device_mfu"] = (round(dev_qps * flops / peak, 6)
                                     if peak else None)
    print(json.dumps(out))


def _peak_flops(platform: str, device_kind: str,
                calibration: dict) -> "tuple[float, str]":
    """(peak dense-matmul FLOP/s, provenance string) for the MFU denominator.

    On real hardware: the device's published bf16 peak, named by kind
    (TPU v5e / "v5 lite" 197 TFLOP/s, v4 275, v5p 459). On the CPU
    fallback: the calibration block's own MEASURED single-thread matmul
    rate — a denominator this very artifact observed, not an assumption
    (VERDICT r4 weak: 1e11 was a placeholder, and the honest number was
    already sitting in the calibration). Nominal 100 GFLOP/s only if the
    calibration itself failed, and the provenance says so.
    """
    peaks = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12, "v5p": 459e12,
             "v5": 197e12, "v6": 918e12}
    if platform == "cpu":
        measured = calibration.get("matmul_gflops_best")
        if measured:
            return measured * 1e9, "measured: calibration matmul_gflops_best"
        return 100e9, "nominal cpu (calibration unavailable)"
    kind = (device_kind
            or os.environ.get("TPURPC_BENCH_DEVICE_KIND", "v5 lite")).lower()
    for key, val in peaks.items():
        if key in kind:
            return val, f"published bf16 peak for {key}"
    return 197e12, "published bf16 peak (unrecognized kind; v5e assumed)"


if __name__ == "__main__":
    main()
