"""On-chip validation + link-ceiling measurement battery.

Runs everything this repo needs from a live TPU in one shot (the axon
tunnel dies for hours at a time — when it's up, harvest fast):

1. ring_window + ring_scatter Pallas kernels vs numpy oracles, compiled
   (interpret=False) on the real chip, across wrap phases.
2. Raw link ceiling with RANDOM data (the tunnel compresses zeros/ones —
   BASELINE.md honesty note): h2d bandwidth, d2h bandwidth, on-device
   d2d copy bandwidth. These are the denominators for "X% of link".
3. Zero-copy `view` experiment (VERDICT r2 next#7): can a jax.Array alias
   ring memory? Tries device-side dlpack round trip and
   unsafe_buffer_pointer identity on a dynamic_slice — records whether
   XLA ever returns an alias (expected: no; dynamic_slice materializes)
   and the measured d2d slice bandwidth that is therefore the `view`
   floor.

Writes ONE JSON blob to stdout and (unless --no-save) to
bench/results/chipcheck.json. Budget-bounded: every phase has a timeout;
a dead tunnel yields {"ok": false, "error": ...} instead of a hang.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _now():
    return time.perf_counter()


def main() -> int:
    out = {"ok": False, "started_unix": time.time()}
    # Hard watchdog: the docstring's no-hang promise. jax calls on a dying
    # tunnel block indefinitely (observed: jax.devices() >10min); SIGALRM
    # cannot interrupt them gracefully, so on fire we emit the error JSON
    # and hard-exit.
    import signal

    budget = int(os.environ.get("CHIPCHECK_BUDGET_S", "1200"))

    def _die(signum, frame):
        out["error"] = f"watchdog: exceeded {budget}s (tunnel hung?)"
        print(json.dumps(out))
        os._exit(2)

    signal.signal(signal.SIGALRM, _die)
    signal.alarm(budget)
    t0 = _now()
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    out["jax_platform"] = dev.platform
    out["device_kind"] = getattr(dev, "device_kind", "?")
    out["devices_init_s"] = round(_now() - t0, 1)
    on_chip = dev.platform not in ("cpu",)
    rng = np.random.default_rng(0)

    # -- 1. kernel validation on the chip -----------------------------------
    kern = {}
    try:
        from tpurpc.ops.ring_scatter import ring_scatter, ring_scatter_reference
        from tpurpc.ops.ring_window import ring_window, ring_window_reference

        interp = not on_chip  # compiled Mosaic on the chip; interpret on CPU
        cap = 1 << 20  # 1 MiB ring
        ring0 = rng.integers(0, 256, cap, dtype=np.uint8)
        cases = [(0, 4096), (4 * 37, 65536), (cap - 2048, 8192),
                 (cap - 4 * 100, 4096), (4 * 513, 4 * 300)]
        t = _now()
        buf = jax.device_put(jnp.asarray(ring0), dev)
        for start, n in cases:
            pay = rng.integers(0, 256, n, dtype=np.uint8)
            want = ring_scatter_reference(np.asarray(buf), pay, start)
            buf = ring_scatter(buf, jax.device_put(jnp.asarray(pay), dev),
                               start, interpret=interp)
            got = np.asarray(buf)
            if not np.array_equal(got, want):
                raise AssertionError(f"scatter mismatch at {start},{n}")
        kern["ring_scatter"] = "ok"
        kern["ring_scatter_compiled"] = not interp
        kern["ring_scatter_s"] = round(_now() - t, 1)
        t = _now()
        snap = np.asarray(buf)
        for head, n in [(0, 4096), (cap - 2048, 8192), (4 * 37, 65536)]:
            want = ring_window_reference(snap, head, n)
            got = np.asarray(ring_window(buf, head, n, interpret=interp))
            if not np.array_equal(got, want):
                raise AssertionError(f"window mismatch at {head},{n}")
        kern["ring_window"] = "ok"
        kern["ring_window_s"] = round(_now() - t, 1)
    except Exception as exc:
        kern["error"] = f"{type(exc).__name__}: {exc}"
    out["kernels"] = kern

    # -- 2. raw link ceiling (random data; the tunnel compresses) ----------
    link = {}
    try:
        n_mb = 8
        x = rng.standard_normal((n_mb << 18,), dtype=np.float32)  # n_mb MiB
        # h2d
        t = _now()
        reps = 0
        while _now() - t < 8.0:
            y = jax.device_put(x, dev)
            y.block_until_ready()
            reps += 1
            if reps >= 8:
                break
        link["h2d_gbps"] = round(reps * x.nbytes / (_now() - t) / 1e9, 3)
        # d2h — one FRESH device array per rep: jax.Array caches its host
        # copy (_npy_value) on first np.asarray, so re-reading one array
        # measures the cache, not the link
        fresh = [jax.device_put(x, dev) + np.float32(i) for i in range(4)]
        for a in fresh:
            a.block_until_ready()
        t = _now()
        reps = 0
        for a in fresh:
            _ = np.asarray(a)
            reps += 1
            if _now() - t > 12.0:
                break
        link["d2h_gbps"] = round(reps * x.nbytes / (_now() - t) / 1e9, 3)
        # on-device copy (the floor for a copying `view`)
        cp = jax.jit(lambda a: a + 0)
        cp(y).block_until_ready()
        t = _now()
        reps = 0
        while _now() - t < 5.0:
            cp(y).block_until_ready()
            reps += 1
            if reps >= 20:
                break
        link["d2d_copy_gbps"] = round(
            2 * reps * x.nbytes / (_now() - t) / 1e9, 3)  # read+write
    except Exception as exc:
        link["error"] = f"{type(exc).__name__}: {exc}"
    out["link"] = link

    # -- 3. zero-copy view experiment ---------------------------------------
    zc = {}
    try:
        big = jax.device_put(
            jnp.asarray(rng.integers(0, 256, 1 << 20, dtype=np.uint8)), dev)
        big.block_until_ready()

        def ptr_of(arr):
            try:
                return arr.addressable_shards[0].data.unsafe_buffer_pointer()
            except Exception:
                return None

        base_ptr = ptr_of(big)
        zc["base_ptr_known"] = base_ptr is not None
        sl = jax.jit(lambda a: jax.lax.dynamic_slice(a, (4096,), (65536,)))(big)
        sl.block_until_ready()
        sl_ptr = ptr_of(sl)
        zc["slice_ptr_known"] = sl_ptr is not None
        if base_ptr is not None and sl_ptr is not None:
            inside = base_ptr <= sl_ptr < base_ptr + (1 << 20)
            zc["slice_aliases_ring"] = bool(inside)
        # dlpack round trip: does importing a slice produce an alias?
        try:
            back = jnp.from_dlpack(sl)  # consumes sl.__dlpack__()
            back.block_until_ready()
            zc["dlpack_roundtrip"] = True
            zc["dlpack_ptr_same"] = (ptr_of(back) == sl_ptr
                                     if sl_ptr is not None else None)
        except Exception as exc:
            zc["dlpack_roundtrip"] = f"failed: {type(exc).__name__}: {exc}"
        # measured slice (view) bandwidth — the copy floor if no aliasing
        slf = jax.jit(lambda a: jax.lax.dynamic_slice(a, (0,), (1 << 19,)))
        slf(big).block_until_ready()
        t = _now()
        reps = 0
        while _now() - t < 5.0:
            slf(big).block_until_ready()
            reps += 1
            if reps >= 40:
                break
        zc["slice_copy_gbps"] = round(
            2 * reps * (1 << 19) / (_now() - t) / 1e9, 3)
    except Exception as exc:
        zc["error"] = f"{type(exc).__name__}: {exc}"
    out["zero_copy"] = zc

    # -- 4. round-5 receive path on THIS backend ---------------------------
    # HbmRing.view's dlpack-alias path is gated to CPU-backed platforms
    # (tpu/hbm_ring.py); on a real chip it must DECLINE (fall back to the
    # materializing slice, billed dma_d2d) because the host-pointer alias
    # has no meaning for HBM. Record which branch actually ran + the
    # ledger's verdict, so the on-chip artifact documents the behavior
    # instead of leaving it inferred.
    hv = {}
    try:
        from tpurpc.tpu import HbmRing, ledger

        ring = HbmRing(1 << 14, device=dev)
        off, n = ring.place(np.arange(1024, dtype=np.float32))
        with ledger.track() as w:
            lease = ring.view(off, n, np.float32, (1024,))
        hv["view_aliased"] = bool(lease.aliased)
        hv["ledger_zero_copy"] = w["zero_copy"]
        hv["ledger_dma_d2d"] = w["dma_d2d"]
        np.testing.assert_array_equal(
            np.asarray(lease.array), np.arange(1024, dtype=np.float32))
        hv["view_bytes_correct"] = True
        lease.release()
    except Exception as exc:
        hv["error"] = f"{type(exc).__name__}: {exc}"
    out["hbm_view"] = hv

    out["ok"] = "error" not in kern and "error" not in link
    out["on_chip"] = on_chip
    out["total_s"] = round(_now() - t0, 1)
    blob = json.dumps(out, indent=1)
    print(blob)
    if "--no-save" not in sys.argv:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "results", "chipcheck.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(blob + "\n")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
