#!/bin/bash
# Regenerate the committed bench/results tables in one command (run on an
# OTHERWISE IDLE host — concurrent load inflates the tail latencies and
# the logs don't carry a load disclaimer). Usage:
#   bash bench/regen_results.sh            # native micro sweep
#   bash bench/regen_results.sh python     # + the (slow) python-path sweep
set -euo pipefail
cd "$(dirname "$0")/.."
BIN=native/build/micro_native
g++ -std=c++17 -O2 native/bench/micro_native.cc native/src/tpurpc_client.cc \
    native/src/tpurpc_server.cc native/src/ring.cc -Inative/include \
    -lpthread -o "$BIN"

OUT=bench/results/micro_native_1core.log
{
  echo "# micro_native: native C client<->server closed-loop, $(nproc)-core host"
  echo "# $(date -u +%FT%TZ) | format: reference examples/cpp/micro-bench log lines (SURVEY.md §6)"
  echo "# reference (IB EDR, multicore): 7.01us p50 / 211K RPC/s streaming (BASELINE.md)"
  for plat in TCP RDMA_BP; do
    echo "#"
    echo "# == platform=$plat =="
    for size in 64 1024 65536; do
      for streaming in 0 1; do
        echo "## platform=$plat req_size=$size streaming=$streaming threads=1"
        GRPC_PLATFORM_TYPE=$plat timeout 120 "$BIN" "$size" 4 1 "$streaming"
      done
    done
  done
  echo "#"
  echo "# == CQ-pipelined async unary (outstanding>1) =="
  for plat in TCP RDMA_BP; do
    for out in 8 64; do
      echo "## platform=$plat req_size=64 streaming=0 threads=1 outstanding=$out"
      GRPC_PLATFORM_TYPE=$plat timeout 120 "$BIN" 64 4 1 0 1 "$out"
    done
  done
  echo "#"
  echo "# == inline-read discipline (TPURPC_NATIVE_INLINE_READ=1) =="
  for size in 64 1024 65536; do
    echo "## platform=RDMA_BP req_size=$size streaming=1 threads=1 inline_read=1"
    GRPC_PLATFORM_TYPE=RDMA_BP TPURPC_NATIVE_INLINE_READ=1 \
      timeout 120 "$BIN" "$size" 4 1 1
  done
} > "$OUT.tmp" && mv "$OUT.tmp" "$OUT"
echo "wrote $OUT"

if [ "${1:-}" = "python" ]; then
  # tmp-then-mv like the native section: an interrupted sweep must not
  # truncate the committed logs
  python -m tpurpc.bench.sweep \
    > bench/results/sweep_python_1core.log.tmp \
    && mv bench/results/sweep_python_1core.log.tmp \
          bench/results/sweep_python_1core.log
  python -m tpurpc.bench.sweep --streaming \
    > bench/results/sweep_python_streaming_1core.log.tmp \
    && mv bench/results/sweep_python_streaming_1core.log.tmp \
          bench/results/sweep_python_streaming_1core.log
  echo "wrote bench/results/sweep_python{,_streaming}_1core.log"
fi
