#!/bin/bash
# Zero-copy send lease A/B (VERDICT r4 next #6): staging-buffer send vs
# serialize-into-the-ring lease, 16KB/128KB/1MB messages over the shm ring.
# Usage: bash bench/send_ab.sh   (run on an otherwise idle host)
set -euo pipefail
cd "$(dirname "$0")/.."
BIN=native/build/send_ab
g++ -std=c++17 -O2 native/bench/send_ab.cc native/src/tpurpc_client.cc \
    native/src/tpurpc_server.cc native/src/ring.cc -Inative/include \
    -lpthread -o "$BIN"

OUT=bench/results/send_ab_1core.log
{
  echo "# send_ab: staging memcpy (A) vs in-ring serialization lease (B), $(nproc)-core host"
  echo "# $(date -u +%FT%TZ) | ring 4MB (default) | reference analog: SendZerocopy pair.cc:793-941"
  echo "# Round-5 verdict: the lease wins where the memcpy is the cost — ~+30%"
  echo "# at 1MB messages (3.4-3.5 vs 2.6-2.7 GB/s), ~+13% at 128KB, within"
  echo "# noise at 16KB (per-message overhead dominates). Found en route: BOTH"
  echo "# modes were 6-8x slower before round 5's wait_event fix — a reader"
  echo "# and a credit-blocked writer sharing one notify fd stole each other's"
  echo "# tokens, so bulk senders moved one ring per 100ms poll slice"
  echo "# (ring_transport.h wait_event; 0.07 -> 5.4 GB/s at 128KB)."
  echo "## platform=RDMA_BP"
  GRPC_PLATFORM_TYPE=RDMA_BP timeout 120 "$BIN" 3
  echo "## repeat (weather control)"
  GRPC_PLATFORM_TYPE=RDMA_BP timeout 120 "$BIN" 3
  echo "#"
  echo "# == varying ring size (the reference's varying-rb-size axis:"
  echo "# draw/varying-rb-size-old/client_bandwidth_RDMA_BP_cli_4_req_131072_ringbuf_2048"
  echo "# = 82.6 Gb/s on IB EDR; here 128KB messages through the shm ring"
  echo "# on one shared core, staging mode = the comparable configuration) =="
  for rb in 1024 2048 8192 32768; do
    echo "## platform=RDMA_BP ring_kb=$rb req_size=131072"
    GRPC_PLATFORM_TYPE=RDMA_BP GRPC_RDMA_RING_BUFFER_SIZE_KB=$rb \
      timeout 120 "$BIN" 2 131072
  done
} | tee "$OUT"
echo "wrote $OUT"
