"""Single source of truth for the accelerator liveness probe.

One SMALL h2d + compute + d2h round trip on the default jax backend; exits
0 iff it completed and the backend is not cpu. Both bench/harvest.sh's
probe() and bench.py's pre-probe run THIS file — the two used to carry
byte-duplicated snippets in two languages and drifted on the one parameter
that matters (the timeout), producing inconsistent liveness verdicts.

The caller MUST bound this process externally (`timeout 150 python
bench/probe.py` / subprocess timeout): a black-holing tunnel hangs jax
calls uninterruptibly, and SIGALRM does not fire while blocked in the C
extension. 150 s is the settled budget — an ALIVE tunnel answers this
small round trip well inside it, while full backend bring-up (minutes) is
deliberately NOT what is being measured.
"""
import numpy as np
import jax

d = jax.devices()[0]
assert d.platform != "cpu"
x = jax.device_put(np.ones(1024, np.float32), d)
y = (x + 1).block_until_ready()
assert float(np.asarray(y)[0]) == 2.0
