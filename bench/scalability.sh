#!/bin/bash
# Multi-client scalability sweep (VERDICT r3 next-round #2): aggregate
# RPC/s + RTT percentiles at 1/8/32/128 client connections, ring vs TCP,
# closed-loop streaming ping-pong (the reference's measured mode) and
# CQ-pipelined unary. The reference's counterpart numbers live in
# examples/cpp/micro-bench/draw/tput-scalability/ (5.23M RPC/s aggregate at
# 128 clients on dedicated multicore IB-EDR hosts); this host is ONE shared
# core carrying client threads + server pollers + handlers, so absolute
# aggregates are not comparable — the axes that matter here are (a) the
# server holding 128 concurrent connections with bounded threads (the
# shared-poller model, tpurpc_server.cc; reference poller.cc:52-106) and
# (b) ring vs TCP at every connection count.
#
# Usage: bash bench/scalability.sh   (run on an otherwise idle host)
set -euo pipefail
cd "$(dirname "$0")/.."
BIN=native/build/micro_native
g++ -std=c++17 -O2 native/bench/micro_native.cc native/src/tpurpc_client.cc \
    native/src/tpurpc_server.cc native/src/ring.cc -Inative/include \
    -lpthread -o "$BIN"

OUT=bench/results/scalability_1core.log
{
  echo "# micro_native multi-client scalability: native C clients<->shared-poller server, $(nproc)-core host"
  echo "# $(date -u +%FT%TZ) | cols: connections x platform | format: reference tput-scalability log lines"
  echo "# reference (IB EDR, multicore, 128 clients): 5.23M RPC/s aggregate (BASELINE.md)"
  for plat in TCP RDMA_BP; do
    for conns in 1 8 32 128; do
      echo "## platform=$plat connections=$conns req_size=64 streaming=1"
      GRPC_PLATFORM_TYPE=$plat timeout 180 "$BIN" 64 4 "$conns" 1
    done
  done
  echo "#"
  echo "# == CQ-pipelined unary, depth 8 per connection =="
  for plat in TCP RDMA_BP; do
    for conns in 1 8 32; do
      echo "## platform=$plat connections=$conns req_size=64 streaming=0 outstanding=8"
      GRPC_PLATFORM_TYPE=$plat timeout 180 "$BIN" 64 4 "$conns" 0 1 8
    done
  done
} | tee "$OUT"
echo "wrote $OUT"
