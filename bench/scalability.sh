#!/bin/bash
# Multi-client scalability sweep (VERDICT r3 next-round #2): aggregate
# RPC/s + RTT percentiles at 1/8/32/128 client connections, ring vs TCP,
# closed-loop streaming ping-pong (the reference's measured mode) and
# CQ-pipelined unary. The reference's counterpart numbers live in
# examples/cpp/micro-bench/draw/tput-scalability/ (5.23M RPC/s aggregate at
# 128 clients on dedicated multicore IB-EDR hosts); this host is ONE shared
# core carrying client threads + server pollers + handlers, so absolute
# aggregates are not comparable — the axes that matter here are (a) the
# server holding 128 concurrent connections with bounded threads (the
# shared-poller model, tpurpc_server.cc; reference poller.cc:52-106) and
# (b) ring vs TCP at every connection count.
#
# Usage: bash bench/scalability.sh   (run on an otherwise idle host)
set -euo pipefail
cd "$(dirname "$0")/.."
BIN=native/build/micro_native
g++ -std=c++17 -O2 native/bench/micro_native.cc native/src/tpurpc_client.cc \
    native/src/tpurpc_server.cc native/src/ring.cc -Inative/include \
    -lpthread -o "$BIN"

OUT=bench/results/scalability_1core.log
{
  echo "# micro_native multi-client scalability: native C clients<->shared-poller server, $(nproc)-core host"
  echo "# $(date -u +%FT%TZ) | cols: connections x platform | format: reference tput-scalability log lines"
  echo "# reference (IB EDR, multicore, 128 clients): 5.23M RPC/s aggregate (BASELINE.md)"
  echo "#"
  echo "# WHERE THE 128-CONN DROOP GOES (round-5 profile, VERDICT r4 weak #5):"
  echo "# the core is 100% saturated at every point (cpu_util ~1.0 in the JSON"
  echo "# lines below) — the fall is per-RPC CPU COST GROWTH, not idle time."
  echo "# Interleaved same-weather reps, ring (reader-thread discipline):"
  echo "#   8 conns ~9 us cpu/RPC; 128 conns ~23 us cpu/RPC (2.5x), while"
  echo "#   ctx-switches/RPC stay ~flat (2.8 -> 2.4) — so it is NOT scheduler"
  echo "#   round trips; each RPC's cycles inflate (cold caches across ~256"
  echo "#   thread stacks + 128 rings, and the reader->waiter wake chain)."
  echo "# The dominant term is the per-channel READER THREAD: with"
  echo "#   TPURPC_NATIVE_INLINE_READ=1 (waiters pump the transport, the"
  echo "#   reference's pollset_work model, SURVEY 3.4) the same 128-conn"
  echo "#   point measures ~11.7 us cpu/RPC and ~2x the throughput; ring"
  echo "#   stays ahead of TCP at every count. Secondary term: ring working"
  echo "#   set (64KB rings at 128 conns beat the default within-weather:"
  echo "#   ~15 vs ~23 us cpu/RPC, reader discipline)."
  echo "# Bound: inline-read trades the CQ async API (needs the reader) for"
  echo "#   the wake-chain elimination; high-conn ring deployments that use"
  echo "#   blocking/streaming calls should set it. The RDMA_BP_INLINE rows"
  echo "#   below are that configuration."
  for plat in TCP RDMA_BP RDMA_BP_INLINE; do
    for conns in 1 8 32 128; do
      echo "## platform=$plat connections=$conns req_size=64 streaming=1"
      if [ "$plat" = "RDMA_BP_INLINE" ]; then
        GRPC_PLATFORM_TYPE=RDMA_BP TPURPC_NATIVE_INLINE_READ=1 \
          timeout 180 "$BIN" 64 4 "$conns" 1
      else
        GRPC_PLATFORM_TYPE=$plat timeout 180 "$BIN" 64 4 "$conns" 1
      fi
    done
  done
  echo "#"
  echo "# == CQ-pipelined unary, depth 8 per connection =="
  for plat in TCP RDMA_BP; do
    for conns in 1 8 32; do
      echo "## platform=$plat connections=$conns req_size=64 streaming=0 outstanding=8"
      GRPC_PLATFORM_TYPE=$plat timeout 180 "$BIN" 64 4 "$conns" 0 1 8
    done
  done
} | tee "$OUT"
echo "wrote $OUT"
