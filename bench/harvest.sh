#!/bin/bash
# Tunnel harvester: loop until the axon TPU tunnel gives us a full artifact
# set, then stop. Each attempt is its own subprocess bounded by `timeout`,
# because a black-holing tunnel hangs any jax call uninterruptibly
# (VERDICT_RESPONSE.md item 1). Probe cheap first; only burn a chipcheck /
# bench budget when the probe proves the data path is actually moving.
#
# Artifacts on success:
#   bench/results/chipcheck.json      (kernels on-chip, link ceiling, aliasing)
#   bench/results/bench_tpu.json      (streaming GB/s + serving QPS/MFU on chip)
# State/log: bench/results/harvest.log
set -u
cd "$(dirname "$0")/.."
LOG=bench/results/harvest.log

# Become a process-group leader so a future replacement can kill the whole
# tree — probe/chipcheck/bench children included — with one signal (killing
# only the shell orphans an in-flight `timeout 1800 python bench.py` for up
# to 30 min of doubled load).
if [ -z "${HARVEST_PGLEADER:-}" ]; then
  HARVEST_PGLEADER=1 exec setsid bash "$0" "$@"
fi

# Single-instance lock: a restarted harvester REPLACES the old loop instead
# of doubling probe load on the shared 1-core host (two loops observed
# interleaving in round 4's log — each probe costs a timeout-bounded jax
# import attempt). Acquisition is atomic (noclobber) so two simultaneous
# starts can't both pass a check-then-write race.
PIDFILE=bench/results/harvest.pid
acquire_lock() { (set -C; echo $$ > "$PIDFILE") 2>/dev/null; }
if ! acquire_lock; then
  oldpid=$(cat "$PIDFILE" 2>/dev/null || true)
  if [ -n "${oldpid:-}" ] && kill -0 "$oldpid" 2>/dev/null \
     && grep -q harvest "/proc/$oldpid/cmdline" 2>/dev/null; then
    echo "=== replacing old harvest loop pid $oldpid with $$ ===" >> "$LOG"
    kill -- "-$oldpid" 2>/dev/null || kill "$oldpid" 2>/dev/null || true
    pkill -P "$oldpid" 2>/dev/null || true   # pre-setsid loops: reap children
    sleep 1
  fi
  rm -f "$PIDFILE"
  acquire_lock || { echo "=== lost lock race; exiting pid $$ ===" >> "$LOG"; exit 0; }
fi
trap 'rm -f "$PIDFILE"' EXIT

echo "=== harvest loop start $(date -u +%FT%TZ) pid $$ ===" >> "$LOG"

probe() {
  # Returns 0 iff a SMALL h2d+compute+d2h round trip completes fast.
  # bench/probe.py is the single probe definition (bench.py's pre-probe
  # runs the same file with the same 150 s bound — keep them in lockstep).
  timeout 150 python bench/probe.py >/dev/null 2>&1
}

attempt=0
while true; do
  attempt=$((attempt + 1))
  ts=$(date -u +%FT%TZ)
  if probe; then
    echo "[$ts] attempt $attempt: probe ALIVE — harvesting" >> "$LOG"
    if [ ! -s bench/results/chipcheck.json ] || ! grep -q '"ok": true' bench/results/chipcheck.json 2>/dev/null; then
      CHIPCHECK_BUDGET_S=1500 timeout 1600 python bench/chipcheck.py \
        > bench/results/chipcheck.stdout 2> bench/results/chipcheck.stderr
      rc=$?
      echo "[$(date -u +%FT%TZ)] chipcheck rc=$rc" >> "$LOG"
    fi
    if [ ! -s bench/results/bench_tpu.json ]; then
      TPURPC_BENCH_READY_S=600 timeout 1800 python bench.py \
        > bench/results/bench_tpu.stdout 2> bench/results/bench_tpu.stderr
      rc=$?
      echo "[$(date -u +%FT%TZ)] bench.py rc=$rc" >> "$LOG"
      # Only keep it as the TPU artifact if it really ran on the chip.
      if [ $rc -eq 0 ] && grep -q '"jax_platform": "tpu"' bench/results/bench_tpu.stdout; then
        tail -1 bench/results/bench_tpu.stdout > bench/results/bench_tpu.json
      elif [ $rc -eq 0 ]; then
        echo "[$(date -u +%FT%TZ)] bench.py fell back (not tpu); not keeping" >> "$LOG"
      fi
    fi
    ck_ok=false; bj_ok=false
    grep -q '"ok": true' bench/results/chipcheck.json 2>/dev/null && ck_ok=true
    [ -s bench/results/bench_tpu.json ] && bj_ok=true
    echo "[$(date -u +%FT%TZ)] state: chipcheck=$ck_ok bench_tpu=$bj_ok" >> "$LOG"
    if $ck_ok && $bj_ok; then
      echo "[$(date -u +%FT%TZ)] HARVEST COMPLETE after $attempt attempts" >> "$LOG"
      exit 0
    fi
  else
    echo "[$ts] attempt $attempt: probe dead" >> "$LOG"
  fi
  sleep 420
done
