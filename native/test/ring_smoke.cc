// Native smoke test for the lock-free ring + send lease, sanitizer-ready.
//
// Built by tools/check.sh (direct g++) and by CMake (`ring_smoke` target),
// with or without TPURPC_SANITIZE={address,thread,undefined}. Under TSan the
// cross-thread test drives the exact producer/consumer protocol the Python
// pair runs over shm: plain data stores ordered by release/acquire fences
// plus the __atomic credit/waiter words. TSan's happens-before engine cannot
// see fence-ordered plain stores (that direction is covered by the
// exhaustive model checker, tpurpc/analysis/ringcheck.py, and suppressed in
// native/sanitize/tsan.supp); everything else — the credit word handshake,
// the lease bookkeeping, init/teardown — is checked for real.
//
//   g++ -std=c++17 -O1 -g -fsanitize=thread native/src/ring.cc \
//       native/test/ring_smoke.cc -o ring_smoke -lpthread
//   TSAN_OPTIONS=suppressions=native/sanitize/tsan.supp ./ring_smoke

#include <sched.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "../src/tpr_obs.h"
#include "../src/tpr_rdv.h"

// Most of the ring ABI comes in through ring_transport.h (via tpr_rdv.h);
// these three are exported by ring.cc but not declared there.
extern "C" {
int tpr_abi_version();
uint64_t tpr_ring_readable(const uint8_t* ring, uint64_t cap, uint64_t head,
                           uint64_t msg_len, uint64_t msg_read, uint64_t seq);
uint64_t tpr_send_fast(uint8_t* ring, uint64_t cap, uint64_t* tail,
                       uint64_t* seq, const uint8_t* status_addr,
                       uint64_t* remote_head, const uint8_t* peer_rxwait_addr,
                       const uint8_t* const* segs, const uint64_t* lens,
                       uint32_t nsegs, uint64_t chunk_size, int* notify_out);
}

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      std::exit(1);                                                     \
    }                                                                   \
  } while (0)

namespace {

constexpr uint64_t kCap = 4096;

// single-thread framing roundtrip: writev -> has_message -> read_into
void test_roundtrip() {
  std::vector<uint8_t> ring(kCap, 0);
  uint64_t tail = 0, wseq = 0;
  uint64_t head = 0, mlen = 0, mread = 0, consumed = 0, rseq = 0;

  uint8_t a[100], b[33];
  std::memset(a, 0xA1, sizeof(a));
  std::memset(b, 0xB2, sizeof(b));
  const uint8_t* segs[2] = {a, b};
  uint64_t lens[2] = {sizeof(a), sizeof(b)};
  CHECK(tpr_ring_writev(ring.data(), kCap, &tail, /*remote_head=*/0, segs,
                        lens, 2, &wseq) == sizeof(a) + sizeof(b));
  CHECK(tpr_ring_has_message(ring.data(), kCap, head, mlen, rseq) == 1);
  CHECK(tpr_ring_readable(ring.data(), kCap, head, mlen, mread, rseq) ==
        sizeof(a) + sizeof(b));

  uint8_t out[256];
  uint64_t n = tpr_ring_read_into(ring.data(), kCap, &head, &mlen, &mread,
                                  out, sizeof(out), &consumed, &rseq);
  CHECK(n == sizeof(a) + sizeof(b));
  for (size_t i = 0; i < sizeof(a); ++i) CHECK(out[i] == 0xA1);
  for (size_t i = 0; i < sizeof(b); ++i) CHECK(out[sizeof(a) + i] == 0xB2);
  CHECK(rseq == 1 && head == tail);
}

// lease: reserve -> fill segments in place -> commit -> read
void test_lease() {
  std::vector<uint8_t> ring(kCap, 0);
  uint64_t tail = 0, wseq = 0;
  uint64_t head = 0, mlen = 0, mread = 0, consumed = 0, rseq = 0;
  CHECK(tpr_ring_max_payload(kCap) == kCap - 24);

  // park the cursors near the end so the reserve WRAPS (two segments)
  uint64_t pre = kCap - 64;  // 8-aligned
  tail = head = pre;
  uint8_t *p1, *p2;
  uint64_t l1, l2;
  uint64_t want = 120;
  CHECK(tpr_ring_reserve(ring.data(), kCap, tail, /*remote_head=*/head, want,
                         &p1, &l1, &p2, &l2) == 1);
  CHECK(l1 + l2 == want && l2 > 0);  // wrapped
  std::memset(p1, 0xC3, l1);
  std::memset(p2, 0xC3, l2);
  // not visible until commit
  CHECK(tpr_ring_has_message(ring.data(), kCap, head, 0, rseq) == 0);
  tpr_ring_commit(ring.data(), kCap, &tail, want, &wseq);
  CHECK(tpr_ring_has_message(ring.data(), kCap, head, 0, rseq) == 1);
  uint8_t out[256];
  CHECK(tpr_ring_read_into(ring.data(), kCap, &head, &mlen, &mread, out,
                           sizeof(out), &consumed, &rseq) == want);
  for (uint64_t i = 0; i < want; ++i) CHECK(out[i] == 0xC3);
}

// two threads, full credit protocol: producer writes via tpr_send_fast
// (credit fold + chunked encode + notify decision), consumer drains and
// publishes its head into the shared status word — the exact shm protocol.
void test_spsc_threads() {
  std::vector<uint8_t> ring(256, 0);  // small: forces wraps + credit stalls
  const uint64_t cap = 256;
  // producer-side "status page": the consumer one-sided-writes its head at
  // +0; the consumer's page carries the read-waiter word at +64.
  alignas(64) static uint8_t prod_status[128];
  alignas(64) static uint8_t cons_status[128];
  std::memset(prod_status, 0, sizeof(prod_status));
  std::memset(cons_status, 0, sizeof(cons_status));

  const int kMsgs = 2000;
  const uint64_t kLen = 48;

  std::thread producer([&] {
    uint64_t tail = 0, seq = 0, remote_head = 0;
    uint8_t payload[kLen];
    for (int m = 0; m < kMsgs; ++m) {
      std::memset(payload, m & 0xFF, sizeof(payload));
      const uint8_t* segs[1] = {payload};
      uint64_t lens[1] = {kLen};
      uint64_t sent = 0;
      while (sent < kLen) {
        int notify = 0;
        const uint8_t* seg0 = payload + sent;
        const uint8_t* s2[1] = {seg0};
        uint64_t l2[1] = {kLen - sent};
        uint64_t got = tpr_send_fast(ring.data(), cap, &tail, &seq,
                                     prod_status, &remote_head,
                                     cons_status + 64, s2, l2, 1,
                                     /*chunk=*/kLen, &notify);
        sent += got;
        if (got == 0) sched_yield();  // stalled for credits
      }
      (void)segs;
      (void)lens;
    }
  });

  std::thread consumer([&] {
    uint64_t head = 0, mlen = 0, mread = 0, consumed = 0, seq = 0;
    uint8_t buf[4096];
    uint64_t total = 0, expect = uint64_t(kMsgs) * kLen;
    uint64_t msg_byte = 0;  // cursor within the current logical message
    while (total < expect) {
      uint64_t n = tpr_ring_read_into(ring.data(), cap, &head, &mlen, &mread,
                                      buf, sizeof(buf), &consumed, &seq);
      CHECK(n != ~0ULL);
      if (n == 0) {
        // advertise the read-waiter word like a parking consumer would,
        // then retract it — exercises the sleep-protocol words under TSan
        tpr_store_u64_seqcst(cons_status + 64, 1);
        if (tpr_ring_has_message(ring.data(), cap, head, mlen, seq) == 0)
          sched_yield();
        tpr_store_u64_seqcst(cons_status + 64, 0);
        continue;
      }
      // verify contents: bytes of message m are (m & 0xFF); messages may
      // arrive split across drains (chunked sends), so track a byte cursor
      for (uint64_t i = 0; i < n; ++i) {
        uint64_t m = (total + i) / kLen;
        CHECK(buf[i] == uint8_t(m & 0xFF));
        (void)msg_byte;
      }
      total += n;
      // publish credits: one-sided store of our head into the producer's
      // status page (+0), release-ordered by the seq_cst store
      tpr_store_u64_seqcst(prod_status, head);
    }
    CHECK(tpr_load_u64_fenced(prod_status) == head);
  });

  producer.join();
  consumer.join();
}

// tpurpc-xray: the obs ring's seqlock protocol — wrap, torn-read
// detection, concurrent writers — exercised for real under TSan (record
// payloads are atomic word stores, so no suppressions are needed here).
void test_obs_ring() {
  if (!tpr_obs_enabled()) {
    std::puts("ring_smoke: native obs disabled by env, skipping");
    return;
  }
  tpr_obs_reset();
  const uint32_t cap = tpr_obs_capacity();
  CHECK(cap >= 64);
  CHECK(tpr_obs_layout_version() == 1);
  CHECK(tpr_obs_shm_name()[0] != '\0');

  // tag intern: stable, idempotent, readable back
  uint16_t t1 = tpr_obs_tag_for("smoke:a");
  uint16_t t2 = tpr_obs_tag_for("smoke:b");
  CHECK(t1 != 0 && t2 != 0 && t1 != t2);
  CHECK(tpr_obs_tag_for("smoke:a") == t1);
  char nm[64];
  CHECK(tpr_obs_tag_name(t1, nm, sizeof nm) == 7);
  CHECK(std::strcmp(nm, "smoke:a") == 0);

  // basic emit/read roundtrip: the record decodes whole
  tpr_obs_emit(tpr_obs::kEvPinWaitBegin, t1, 123, -456);
  std::vector<uint8_t> buf((size_t)cap * tpr_obs::kRecordBytes);
  int n = tpr_obs_read(buf.data(), (int)cap);
  CHECK(n == 1);
  uint64_t w[4];
  std::memcpy(w, buf.data(), sizeof w);
  CHECK((w[1] & 0xFFFF) == tpr_obs::kEvPinWaitBegin);
  CHECK(((w[1] >> 16) & 0xFFFF) == t1);
  CHECK((int64_t)w[2] == 123 && (int64_t)w[3] == -456);
  CHECK(w[0] != 0);  // CLOCK_MONOTONIC stamp

  // wrap: capacity + 37 emits leave exactly `capacity` readable records,
  // all from the newest window (a1 encodes the emission index)
  tpr_obs_reset();
  const uint64_t total = (uint64_t)cap + 37;
  for (uint64_t i = 0; i < total; ++i)
    tpr_obs_emit(tpr_obs::kEvPinWaitEnd, t1, (int64_t)i, 0);
  n = tpr_obs_read(buf.data(), (int)cap);
  CHECK(n == (int)cap);
  for (int i = 0; i < n; ++i) {
    std::memcpy(w, buf.data() + (size_t)i * tpr_obs::kRecordBytes, sizeof w);
    CHECK(w[2] >= total - cap && w[2] < total);
  }

  // concurrent writers + one racing reader: every record the reader
  // accepts must be internally whole (each writer stamps a1 == ~a2, so
  // any torn mix of two records breaks the invariant) — the per-slot
  // seqlock recheck is the only thing standing between this and a
  // corrupt read.
  tpr_obs_reset();
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> torn{0};
  std::thread obs_reader([&] {
    std::vector<uint8_t> rb((size_t)cap * tpr_obs::kRecordBytes);
    while (!stop.load()) {
      int k = tpr_obs_read(rb.data(), (int)cap);
      for (int i = 0; i < k; ++i) {
        uint64_t v[4];
        std::memcpy(v, rb.data() + (size_t)i * tpr_obs::kRecordBytes,
                    sizeof v);
        if (v[2] != ~v[3]) torn.fetch_add(1);
      }
    }
  });
  const int kWriters = 4;
  const uint64_t kPerWriter = 20000;
  std::vector<std::thread> obs_writers;
  for (int wi = 0; wi < kWriters; ++wi) {
    obs_writers.emplace_back([&, wi] {
      for (uint64_t i = 0; i < kPerWriter; ++i) {
        uint64_t v = ((uint64_t)(wi + 1) << 32) | i;
        tpr_obs_emit(tpr_obs::kEvDlvStallBegin, t2, (int64_t)v,
                     (int64_t)~v);
      }
    });
  }
  for (auto &th : obs_writers) th.join();
  stop.store(true);
  obs_reader.join();
  CHECK(torn.load() == 0);

  // after the dust settles every slot holds one whole record, and the
  // emitted counter saw every write (wraps overwrite, never drop)
  n = tpr_obs_read(buf.data(), (int)cap);
  CHECK(n == (int)cap);
  for (int i = 0; i < n; ++i) {
    std::memcpy(w, buf.data() + (size_t)i * tpr_obs::kRecordBytes, sizeof w);
    CHECK(w[2] == ~w[3]);
  }
  uint64_t mets[tpr_obs::kNumMetrics] = {0};
  tpr_obs_counters(mets, (int)tpr_obs::kNumMetrics);
  CHECK(mets[tpr_obs::kMetEmitted] == kWriters * kPerWriter);
  tpr_obs_reset();
}

// Loopback harness for the rendezvous ladder: two Links wired back to
// back, framed control frames delivered synchronously (each side's
// send_frame calls the peer's on_frame and advances both frame counters,
// keeping the ctrl-ring ordering gate consistent), claim waits pumped by
// draining our own rx ring — the inline-read discipline in miniature.
struct RdvPeer {
  tpr_rdv::Link link;
  RdvPeer *peer = nullptr;
  std::vector<uint8_t> delivered;
  uint8_t last_flags = 0;

  explicit RdvPeer(const char *name) : link(name) {
    link.send_frame = [this](uint8_t type, uint32_t sid,
                             const std::string &p) {
      link.frames_sent.fetch_add(1, std::memory_order_release);
      peer->link.on_frame(type, sid,
                          reinterpret_cast<const uint8_t *>(p.data()),
                          p.size());
      peer->link.frames_dispatched.fetch_add(1, std::memory_order_release);
      peer->link.ctrl_drain();  // post-dispatch gate lift, as the conns do
      return true;
    };
    link.deliver = [this](uint32_t sid, uint8_t flags, uint8_t *data,
                          size_t len) {
      (void)sid;
      delivered.assign(data, data + len);
      last_flags = flags;
      CHECK(tpr_rdv::settle(data));  // region pointer, settled exactly once
    };
    link.wake = [] {};
    // The pump stands in for BOTH dispatch loops: the real conns poll
    // their rx rings while hot; a single-threaded harness has to drain
    // the peer's ring too or ring-borne ops would strand.
    link.pump = [this](const std::function<bool()> &pred,
                       std::chrono::steady_clock::time_point dl) {
      while (!pred() && std::chrono::steady_clock::now() < dl) {
        int n = link.ctrl_drain();
        if (peer) n += peer->link.ctrl_drain();
        if (n == 0) sched_yield();
      }
    };
  }
};

void test_rdv_loopback() {
  if (!tpr_rdv::enabled() || !tpr_rdv::ctrl_enabled()) {
    std::puts("ring_smoke: rdv disabled by env, skipping ladder");
    return;
  }
  RdvPeer a("cli"), b("srv");
  a.peer = &b;
  b.peer = &a;
  // capability hello both ways (the PING payloads the conns exchange)
  std::string ha = a.link.hello_payload(), hb = b.link.hello_payload();
  CHECK(b.link.maybe_hello(reinterpret_cast<const uint8_t *>(ha.data()),
                           ha.size()));
  CHECK(a.link.maybe_hello(reinterpret_cast<const uint8_t *>(hb.data()),
                           hb.size()));
  CHECK(a.link.negotiated.load() && b.link.negotiated.load());
  // a plain PING must NOT negotiate (un-negotiated peers stay framed)
  tpr_rdv::Link lone("lone");
  CHECK(!lone.maybe_hello(reinterpret_cast<const uint8_t *>("p"), 1));
  CHECK(!lone.negotiated.load());
  CHECK(!lone.eligible(tpr_rdv::min_bytes()));

  // sub-threshold payloads are never eligible — they stay framed
  CHECK(!a.link.eligible(tpr_rdv::min_bytes() - 1));
  CHECK(a.link.eligible(tpr_rdv::min_bytes()));

  // the ladder: one transfer per size class, byte-exact, region-settled
  const uint64_t before_sent =
      tpr_rdv::g_counters[tpr_rdv::kCtrRdvSent].load();
  const size_t sizes[] = {size_t(tpr_rdv::min_bytes()), 1u << 20,
                          (1u << 22) + 5};  // odd tail crosses class pad
  uint64_t total_bytes = 0;
  for (size_t n : sizes) {
    std::vector<uint8_t> payload(n);
    for (size_t i = 0; i < n; ++i)
      payload[i] = uint8_t((i * 31 + n) & 0xFF);
    b.delivered.clear();
    CHECK(a.link.send_message(7, /*flags=*/0x01, payload.data(), n));
    b.link.ctrl_drain();  // the receiver's hot dispatch poll
    CHECK(b.delivered.size() == n);
    CHECK(std::memcmp(b.delivered.data(), payload.data(), n) == 0);
    CHECK(b.last_flags == 0x01);
    total_bytes += n;
  }
  CHECK(tpr_rdv::g_counters[tpr_rdv::kCtrRdvSent].load() ==
        before_sent + 3);

  // ctrl-ring discipline: the ladder's control ops moved as ring records
  // (the kicks that did fire targeted a parked consumer). Steady state —
  // repeat transfers with both consumers hot — posts records with ZERO
  // framed control ops and ZERO kicks: the zero-wakeup acceptance bar.
  CHECK(tpr_rdv::g_counters[tpr_rdv::kCtrCtrlRecords].load() > 0);
  a.link.ctrl_drain();
  b.link.ctrl_drain();
  const uint64_t frames0 =
      tpr_rdv::g_counters[tpr_rdv::kCtrCtrlFrames].load();
  const uint64_t kicks0 = tpr_rdv::g_counters[tpr_rdv::kCtrCtrlKicks].load();
  for (int rep = 0; rep < 4; ++rep) {
    std::vector<uint8_t> payload(1u << 20, uint8_t(rep));
    b.delivered.clear();
    CHECK(a.link.send_message(9, 0, payload.data(), payload.size()));
    b.link.ctrl_drain();
    CHECK(b.delivered.size() == payload.size());
  }
  CHECK(tpr_rdv::g_counters[tpr_rdv::kCtrCtrlFrames].load() == frames0);
  CHECK(tpr_rdv::g_counters[tpr_rdv::kCtrCtrlKicks].load() == kicks0);

  // park/kick: a parked consumer's producer goes framed with a CTRL_KICK
  // (posted record + kick frame), and the record still lands in order
  a.link.ctrl_park();
  {
    std::vector<uint8_t> payload(1u << 20, 0x5A);
    b.delivered.clear();
    CHECK(a.link.send_message(11, 0, payload.data(), payload.size()));
    b.link.ctrl_drain();
    CHECK(b.delivered.size() == payload.size());
  }
  a.link.close();
  b.link.close();
  lone.close();
}

// A dead link refuses new sends (framed fallback) instead of hanging —
// the never-hang half of the fallback contract, claim waiters included.
void test_rdv_closed_link_falls_back() {
  if (!tpr_rdv::enabled() || !tpr_rdv::ctrl_enabled()) return;
  RdvPeer a("cli2"), b("srv2");
  a.peer = &b;
  b.peer = &a;
  std::string ha = a.link.hello_payload(), hb = b.link.hello_payload();
  b.link.maybe_hello(reinterpret_cast<const uint8_t *>(ha.data()),
                     ha.size());
  a.link.maybe_hello(reinterpret_cast<const uint8_t *>(hb.data()),
                     hb.size());
  b.link.close();  // peer dies: its on_frame goes quiet
  b.link.ctrl_drain();
  std::vector<uint8_t> payload(1u << 20, 0x77);
  auto t0 = std::chrono::steady_clock::now();
  // the peer never claims; send_message must return false (framed
  // fallback) within the claim timeout, never hang
  CHECK(!a.link.send_message(13, 0, payload.data(), payload.size()));
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0).count();
  CHECK(waited < tpr_rdv::claim_timeout_s() + 2.0);
  a.link.close();
}

}  // namespace

int main() {
  CHECK(tpr_abi_version() == 7);
  test_roundtrip();
  test_lease();
  test_spsc_threads();
  test_obs_ring();
  test_rdv_loopback();
  test_rdv_closed_link_falls_back();
  std::puts("ring_smoke: OK");
  return 0;
}
