// A/B microbench for the zero-copy send lease (VERDICT r4 next #6):
// does eliminating the staging memcpy on the ring send path matter?
//
//   A (staging):  produce payload into an app buffer (one pattern write),
//                 then tpr_call_send — which memcpys it into the peer ring
//                 (tpr_ring_writev copy_in). Two passes over the bytes.
//   B (lease):    tpr_call_send_reserve — produce the SAME payload pattern
//                 directly into the reserved ring span — commit. One pass.
//
// The producer work (one pattern write over the payload) is identical in
// both modes, so the measured delta is exactly the staging memcpy the
// reference's SendZerocopy eliminates (pair.cc:793-941; its NIC moves the
// bytes instead of the CPU — in shm the producing store IS the move).
//
// Server side: handler-API sink draining the stream (no echo traffic).
// Build+run: bash bench/send_ab.sh  -> bench/results/send_ab_1core.log
//
// Output: one line + one JSON line per (mode, size).

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "tpurpc/client.h"
#include "tpurpc/server.h"

static int sink_handler(tpr_server_call *call, void *) {
  uint8_t *data;
  size_t len;
  while (tpr_srv_recv(call, &data, &len) == 1) tpr_srv_buf_free(data);
  static const uint8_t ok = 1;
  tpr_srv_send(call, &ok, 1);
  return 0;
}

// the "serialization" both modes perform: one full pass writing the bytes
static void produce(uint8_t *dst, size_t len, uint8_t salt) {
  memset(dst, 0xA0 ^ salt, len);
}

int main(int argc, char **argv) {
  double secs = argc > 1 ? atof(argv[1]) : 3.0;
  size_t only_size = argc > 2 ? (size_t)atoll(argv[2]) : 0;  // 0 = all

  tpr_server *srv = tpr_server_create(0);
  if (!srv) return 1;
  tpr_server_register(srv, "/ab.Sink/Drain", sink_handler, nullptr);
  if (tpr_server_start(srv) != 0) return 1;
  int port = tpr_server_port(srv);

  const size_t sizes[] = {16 * 1024, 128 * 1024, 1024 * 1024};
  for (size_t size : sizes) {
    if (only_size && size != only_size) continue;
    for (int mode = 0; mode < 2; ++mode) {  // 0 = A staging, 1 = B lease
      tpr_channel *ch = tpr_channel_create("127.0.0.1", port, 5000);
      if (!ch) return 1;
      tpr_call *c = tpr_call_start(ch, "/ab.Sink/Drain", nullptr, 0, 0);
      if (!c) return 1;
      std::vector<uint8_t> staging(size);
      uint64_t sent = 0, msgs = 0;
      bool lease_ok = true;
      auto t0 = std::chrono::steady_clock::now();
      auto t_end = t0 + std::chrono::duration<double>(secs);
      while (std::chrono::steady_clock::now() < t_end) {
        uint8_t salt = (uint8_t)msgs;
        if (mode == 0) {
          produce(staging.data(), size, salt);
          if (tpr_call_send(c, staging.data(), size, 0) != 0) return 1;
        } else {
          uint8_t *p1, *p2;
          size_t l1, l2;
          if (tpr_call_send_reserve(c, size, 0, &p1, &l1, &p2, &l2) != 0) {
            lease_ok = false;  // e.g. TCP platform: lease ineligible
            break;
          }
          produce(p1, l1, salt);
          if (l2) produce(p2, l2, salt);
          if (tpr_call_send_commit(c) != 0) return 1;
        }
        sent += size;
        ++msgs;
      }
      if (!lease_ok) {
        printf("mode=lease size=%zu SKIP (lease ineligible on this "
               "platform)\n", size);
        tpr_call_cancel(c);
        tpr_call_destroy(c);
        tpr_channel_destroy(ch);
        continue;
      }
      // half-close and wait for the sink's ack so every byte is DRAINED
      // (otherwise the timer would stop while the ring still holds data);
      // writes_done sends the pure half-close marker, NOT an empty message
      tpr_call_writes_done(c);
      uint8_t *resp;
      size_t rlen;
      if (tpr_call_recv(c, &resp, &rlen) == 1) tpr_buf_free(resp);
      int st = tpr_call_finish(c, nullptr, 0);
      double dt = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0).count();
      tpr_call_destroy(c);
      tpr_channel_destroy(ch);
      if (st != TPR_OK) {
        fprintf(stderr, "finish status %d\n", st);
        return 1;
      }
      double gbps = (double)sent / dt / 1e9;
      const char *m = mode == 0 ? "staging" : "lease";
      printf("mode=%s size=%zu msgs=%llu %.3f GB/s\n", m, size,
             (unsigned long long)msgs, gbps);
      printf("{\"bench\": \"send_ab\", \"mode\": \"%s\", \"size\": %zu, "
             "\"msgs\": %llu, \"secs\": %.2f, \"gbps\": %.3f}\n",
             m, size, (unsigned long long)msgs, dt, gbps);
    }
  }
  tpr_server_destroy(srv);
  return 0;
}
