// Native micro-benchmark: closed-loop small-RPC ping-pong, C client vs C
// server in one process over loopback TCP — the number the reference
// commits as examples/cpp/micro-bench logs
// (draw/latency/client_latency_RDMA_BP_size_64_streaming_true.log:
// 7.01 us p50, 211K RPC/s on IB EDR; SURVEY.md §6). This measures tpurpc's
// native data loop with the Python framework out of the picture — the
// framework-overhead headroom quantifier VERDICT r2 next#3 asked for.
//
// Build: g++ -std=c++17 -O2 native/bench/micro_native.cc \
//          native/src/tpurpc_client.cc native/src/tpurpc_server.cc \
//          -Inative/include -lpthread -o /tmp/micro_native
// Run:   /tmp/micro_native [req_size=64] [duration_s=5] [threads=1]
//                          [streaming=0|1] [use_cb=1] [outstanding=1]
// streaming=1 is the reference's measured configuration (its committed
// latency logs are `streaming_true`): ONE bidi call per thread, ping-pong
// messages — call setup/teardown off the per-RPC path.
// outstanding>1 (with streaming=0) pipelines that many unary calls per
// thread through the CQ async API — the reference's `concurrent` axis
// (mb_client's concurrency flag in its tput-scalability sweeps): completions
// amortize wakeups, so rate rises even on one core while per-RPC RTT grows.
//
// Output: the reference's log line shape —
//   "Rate N RPCs/s, TX Bandwidth M Mb/s, RTT (us) mean A P50 B P99 C"
// then one JSON line for machine consumption.

#include <sys/resource.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include "tpurpc/client.h"
#include "tpurpc/server.h"

static int echo_handler(tpr_server_call *call, void *) {
  uint8_t *data;
  size_t len;
  while (tpr_srv_recv(call, &data, &len) == 1) {
    tpr_srv_send(call, data, len);
    tpr_srv_buf_free(data);
  }
  return 0;
}

// callback-API echo: runs on the reader thread, no handler-thread handoff
static int echo_cb(tpr_server_call *call, const uint8_t *data, size_t len,
                   void *) {
  tpr_srv_send(call, data, len);
  return 0;
}

int main(int argc, char **argv) {
  size_t req_size = argc > 1 ? (size_t)atoll(argv[1]) : 64;
  double duration_s = argc > 2 ? atof(argv[2]) : 5.0;
  int threads = argc > 3 ? atoi(argv[3]) : 1;
  int streaming = argc > 4 ? atoi(argv[4]) : 0;
  int use_cb = argc > 5 ? atoi(argv[5]) : 1;  // callback API by default
  int outstanding = argc > 6 ? atoi(argv[6]) : 1;  // CQ pipeline depth
  // Depth only applies to the CQ unary mode; normalize so the JSON line
  // never attributes one-in-flight numbers to a pipelined depth.
  if (streaming || outstanding < 1) outstanding = 1;

  tpr_server *srv = tpr_server_create(0);
  if (!srv) { fprintf(stderr, "server create failed\n"); return 1; }
  if (use_cb)
    tpr_server_register_callback(srv, "/bench.Echo/Echo", echo_cb, nullptr);
  else
    tpr_server_register(srv, "/bench.Echo/Echo", echo_handler, nullptr);
  if (tpr_server_start(srv) != 0) { fprintf(stderr, "start failed\n"); return 1; }
  int port = tpr_server_port(srv);

  std::atomic<uint64_t> total_rpcs{0};
  std::vector<std::vector<double>> lat_us_per_thread(threads);
  std::vector<std::thread> workers;
  auto t_end = std::chrono::steady_clock::now() +
               std::chrono::duration<double>(duration_s);

  for (int t = 0; t < threads; ++t) {
    workers.emplace_back([&, t]() {
      tpr_channel *ch = tpr_channel_create("127.0.0.1", port, 5000);
      if (!ch) { fprintf(stderr, "connect failed\n"); return; }
      std::vector<uint8_t> payload(req_size, 0xAB);
      auto &lat = lat_us_per_thread[t];
      lat.reserve(1 << 20);
      if (!streaming && outstanding > 1) {
        // CQ-pipelined unary: keep K calls in flight; each FINISH
        // completion immediately refills its slot.
        tpr_cq *cq = tpr_cq_create();
        struct Slot {
          tpr_call *call = nullptr;
          std::chrono::steady_clock::time_point t0;
        };
        std::vector<Slot> slots(outstanding);
        auto start_slot = [&](size_t i) {
          slots[i].t0 = std::chrono::steady_clock::now();
          slots[i].call = tpr_unary_call_cq(ch, "/bench.Echo/Echo",
                                            payload.data(), payload.size(),
                                            5000, cq, (void *)(uintptr_t)i);
          return slots[i].call != nullptr;
        };
        size_t inflight = 0;
        for (size_t i = 0; i < (size_t)outstanding; ++i)
          if (start_slot(i)) inflight++;
        while (inflight > 0) {
          tpr_event ev;
          if (tpr_cq_next(cq, &ev, 10000) != 1) break;  // > call deadline
          if (ev.type != TPR_EV_FINISH) continue;
          size_t i = (size_t)(uintptr_t)ev.tag;
          if (ev.data) tpr_buf_free(ev.data);
          tpr_call_destroy(slots[i].call);
          slots[i].call = nullptr;
          inflight--;
          if (ev.status != TPR_OK) continue;  // drain; don't refill
          auto dt = std::chrono::duration<double, std::micro>(
                        std::chrono::steady_clock::now() - slots[i].t0)
                        .count();
          lat.push_back(dt);
          total_rpcs.fetch_add(1, std::memory_order_relaxed);
          if (std::chrono::steady_clock::now() < t_end && start_slot(i))
            inflight++;
        }
        // The drain can bail with calls still live (stalled server): every
        // call must be destroyed BEFORE the queue (client.h destroy order),
        // or channel teardown drains completions into a freed cq.
        for (auto &s : slots)
          if (s.call) {
            tpr_call_cancel(s.call);
            tpr_call_destroy(s.call);
          }
        tpr_cq_shutdown(cq);
        tpr_cq_destroy(cq);
      } else if (streaming) {
        // one bidi call for the whole run: message round trips only
        tpr_call *c = tpr_call_start(ch, "/bench.Echo/Echo", nullptr, 0, 0);
        if (!c) { tpr_channel_destroy(ch); return; }
        while (std::chrono::steady_clock::now() < t_end) {
          auto t0 = std::chrono::steady_clock::now();
          if (tpr_call_send(c, payload.data(), payload.size(), 0) != 0) break;
          uint8_t *resp; size_t rlen;
          if (tpr_call_recv(c, &resp, &rlen) != 1) break;
          tpr_buf_free(resp);
          auto dt = std::chrono::duration<double, std::micro>(
                        std::chrono::steady_clock::now() - t0).count();
          lat.push_back(dt);
          total_rpcs.fetch_add(1, std::memory_order_relaxed);
        }
        tpr_call_cancel(c);
        tpr_call_destroy(c);
      } else {
        while (std::chrono::steady_clock::now() < t_end) {
          auto t0 = std::chrono::steady_clock::now();
          tpr_call *c = tpr_call_start(ch, "/bench.Echo/Echo", nullptr, 0,
                                       5000);
          if (!c) break;
          if (tpr_call_send(c, payload.data(), payload.size(), 1) != 0) {
            tpr_call_destroy(c);
            break;
          }
          uint8_t *resp; size_t rlen;
          int got = tpr_call_recv(c, &resp, &rlen);
          if (got == 1) tpr_buf_free(resp);
          int st = tpr_call_finish(c, nullptr, 0);
          tpr_call_destroy(c);
          if (got != 1 || st != TPR_OK) break;
          auto dt = std::chrono::duration<double, std::micro>(
                        std::chrono::steady_clock::now() - t0).count();
          lat.push_back(dt);
          total_rpcs.fetch_add(1, std::memory_order_relaxed);
        }
      }
      tpr_channel_destroy(ch);
    });
  }
  auto t_start = std::chrono::steady_clock::now();
  struct rusage ru_start;  // bracket rusage to the SAME window as elapsed:
  getrusage(RUSAGE_SELF, &ru_start);  // server setup/spawn cost excluded
  for (auto &w : workers) w.join();
  double elapsed = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - t_start).count();
  struct rusage ru_end;
  getrusage(RUSAGE_SELF, &ru_end);
  tpr_server_destroy(srv);

  std::vector<double> lat;
  for (auto &v : lat_us_per_thread) lat.insert(lat.end(), v.begin(), v.end());
  if (lat.empty()) { fprintf(stderr, "no completed RPCs\n"); return 1; }
  std::sort(lat.begin(), lat.end());
  auto pct = [&](double p) {
    size_t i = (size_t)(p / 100.0 * (double)(lat.size() - 1));
    return lat[i];
  };
  double mean = 0;
  for (double x : lat) mean += x;
  mean /= (double)lat.size();
  uint64_t n = total_rpcs.load();
  double rate = (double)n / elapsed;
  double tx_mbps = rate * (double)req_size * 8.0 / 1e6;

  // the reference's periodic log line shape (SURVEY.md §6)
  // Where do the cycles go? (VERDICT r4 weak #5: the 128-conn droop needs
  // a cause, not a shrug.) Whole-process rusage deltas over the measured
  // window — clients + readers + server pollers share this process —
  // turned into per-RPC unit costs: cpu_us_per_rpc separates "core
  // saturated, work costs more per op" (number grows) from "core idle,
  // scheduling stalls" (cpu share falls); csw_per_rpc counts scheduler
  // round trips per RPC. (Per-worker channel connects happen inside the
  // window — same bias the rate denominator has.)
  auto tv_s = [](const struct timeval &tv) {
    return tv.tv_sec + tv.tv_usec / 1e6;
  };
  double cpu_s = (tv_s(ru_end.ru_utime) - tv_s(ru_start.ru_utime)) +
                 (tv_s(ru_end.ru_stime) - tv_s(ru_start.ru_stime));
  long nvcsw = ru_end.ru_nvcsw - ru_start.ru_nvcsw;
  long nivcsw = ru_end.ru_nivcsw - ru_start.ru_nivcsw;
  double cpu_us_per_rpc = n ? cpu_s * 1e6 / (double)n : 0.0;
  double csw_per_rpc = n ? (double)(nvcsw + nivcsw) / (double)n : 0.0;
  // config provenance for the JSON line: the sweep's RDMA_BP_INLINE rows
  // differ from RDMA_BP only by env, and machine consumers must not need
  // to correlate comment headers to tell them apart
  const char *plat = getenv("GRPC_PLATFORM_TYPE");
  const char *inl = getenv("TPURPC_NATIVE_INLINE_READ");

  // the reference's periodic log line shape (SURVEY.md §6)
  printf("Rate %.0f RPCs/s, TX Bandwidth %.2f Mb/s, RTT (us) mean %.2f "
         "P50 %.2f P99 %.2f\n", rate, tx_mbps, mean, pct(50), pct(99));
  printf("{\"bench\": \"micro_native\", \"req_size\": %zu, \"threads\": %d, "
         "\"streaming\": %s, \"outstanding\": %d, "
         "\"platform\": \"%s\", \"inline_read\": %s, "
         "\"duration_s\": %.1f, \"rpcs\": %llu, \"rate_rps\": %.0f, "
         "\"rtt_us_mean\": %.2f, \"rtt_us_p50\": %.2f, \"rtt_us_p99\": %.2f, "
         "\"cpu_s\": %.2f, \"cpu_util\": %.3f, \"cpu_us_per_rpc\": %.2f, "
         "\"nvcsw\": %ld, \"nivcsw\": %ld, \"csw_per_rpc\": %.2f}\n",
         req_size, threads, streaming ? "true" : "false", outstanding,
         plat ? plat : "TCP",
         (inl && inl[0] == '1') ? "true" : "false", elapsed,
         (unsigned long long)n, rate, mean, pct(50), pct(99),
         cpu_s, elapsed > 0 ? cpu_s / elapsed : 0.0, cpu_us_per_rpc,
         nvcsw, nivcsw, csw_per_rpc);
  return 0;
}
