// tpurpc C++ server API — RAII wrapper over server.h; counterpart of
// client.hpp. Mirrors the reference's sync-server shape (ServerBuilder +
// service methods, src/cpp/server/server_builder.cc) at tpurpc scale:
//
//   tpurpc::Server srv(0);                       // ephemeral port
//   srv.AddMethod("/pkg.Svc/Echo",
//                 [](tpurpc::ServerCall &call) {
//                   std::string msg;
//                   while (call.Read(&msg)) call.Write("echo:" + msg);
//                   return 0;                    // OK
//                 });
//   srv.Start();
//   int port = srv.port();
#ifndef TPURPC_SERVER_HPP
#define TPURPC_SERVER_HPP

#include <cstdint>
#include <functional>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "server.h"

namespace tpurpc {

class ServerCall {
 public:
  explicit ServerCall(tpr_server_call *c) : c_(c) {}

  // Next request; false at client half-close (or cancellation — check
  // cancelled() to distinguish).
  bool Read(std::string *out) {
    uint8_t *data = nullptr;
    size_t len = 0;
    int r = tpr_srv_recv(c_, &data, &len);
    if (r != 1) {
      cancelled_ = (r < 0);
      return false;
    }
    out->assign(reinterpret_cast<char *>(data), len);
    tpr_srv_buf_free(data);
    return true;
  }

  bool Write(const std::string &msg) {
    return tpr_srv_send(c_, reinterpret_cast<const uint8_t *>(msg.data()),
                        msg.size()) == 0;
  }

  std::string method() const { return tpr_srv_method(c_); }
  int64_t deadline_us() const { return tpr_srv_deadline_us(c_); }
  bool cancelled() const { return cancelled_; }
  void SetDetails(const std::string &d) { tpr_srv_set_details(c_, d.c_str()); }

 private:
  tpr_server_call *c_;
  bool cancelled_ = false;
};

class Server {
 public:
  using Handler = std::function<int(ServerCall &)>;

  explicit Server(int port) : srv_(tpr_server_create(port)) {
    if (!srv_) throw std::runtime_error("tpurpc: bind failed");
  }
  ~Server() {
    if (srv_) tpr_server_destroy(srv_);
  }
  Server(const Server &) = delete;
  Server &operator=(const Server &) = delete;

  void AddMethod(const std::string &method, Handler h) {
    handlers_.push_back(std::make_unique<Handler>(std::move(h)));
    tpr_server_register(srv_, method.c_str(), &Server::trampoline,
                        handlers_.back().get());
  }

  void Start() { tpr_server_start(srv_); }
  int port() const { return tpr_server_port(srv_); }

 private:
  static int trampoline(tpr_server_call *c, void *ud) {
    ServerCall call(c);
    return (*static_cast<Handler *>(ud))(call);
  }

  tpr_server *srv_;
  std::vector<std::unique_ptr<Handler>> handlers_;
};

}  // namespace tpurpc

#endif  // TPURPC_SERVER_HPP
