/* tpurpc C client API — the app-facing native surface (SURVEY.md §1 L7).
 *
 * The reference ships a full C++ application API (src/cpp/ + include/grpcpp/,
 * 14,328 LoC) above its C core surface (src/core/lib/surface/). tpurpc's
 * equivalent is deliberately small: a blocking C API over the tpurpc native
 * framing (tpurpc/rpc/frame.py documents the wire format), speaking TCP to
 * any tpurpc server — including ring-platform and TPU-platform listeners,
 * whose accept loops protocol-sniff the preface (tpurpc/rpc/server.py).
 * A header-only C++ RAII wrapper lives in tpurpc/client.hpp.
 *
 * Concurrency model: one background reader thread per channel demuxes frames
 * to calls (the moral equivalent of grpc's completion-queue plumbing,
 * completion_queue.cc:393, collapsed to blocking calls); any number of app
 * threads may run calls on one channel concurrently.
 *
 * All functions return 0 / a valid pointer on success unless noted.
 * Status codes match gRPC's numbering (tpurpc/rpc/status.py).
 */
#ifndef TPURPC_CLIENT_H
#define TPURPC_CLIENT_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tpr_channel tpr_channel;
typedef struct tpr_call tpr_call;

/* -- status codes (grpc numbering) -- */
enum {
  TPR_OK = 0,
  TPR_CANCELLED = 1,
  TPR_UNKNOWN = 2,
  TPR_DEADLINE_EXCEEDED = 4,
  TPR_UNIMPLEMENTED = 12,
  TPR_INTERNAL = 13,
  TPR_UNAVAILABLE = 14
};

/* Connect a channel. timeout_ms bounds the TCP connect. NULL on failure. */
tpr_channel *tpr_channel_create(const char *host, int port, int timeout_ms);
void tpr_channel_destroy(tpr_channel *ch);

/* Round-trip a PING frame; returns microseconds, or -1 on failure. */
int64_t tpr_channel_ping(tpr_channel *ch, int timeout_ms);

/* Start a call. metadata: flat array of 2*n_md C strings (k,v,k,v,...);
 * timeout_ms <= 0 means no deadline. NULL when the channel is dead or the
 * server sent GOAWAY (max_connection_age drain) — in-flight calls still
 * complete, but new calls need a fresh tpr_channel_create. */
tpr_call *tpr_call_start(tpr_channel *ch, const char *method,
                         const char *const *metadata, size_t n_md,
                         int timeout_ms);

/* Send one request message. end_stream half-closes after this message. */
int tpr_call_send(tpr_call *c, const uint8_t *data, size_t len,
                  int end_stream);

/* Half-close without a message (client finished sending). */
int tpr_call_writes_done(tpr_call *c);

/* Receive the next response message. Returns 1 with *data/*len set (caller
 * frees with tpr_buf_free), 0 at end of the response stream (trailers seen),
 * -1 on transport error / deadline. */
int tpr_call_recv(tpr_call *c, uint8_t **data, size_t *len);

/* Block until trailers; returns the status code. details (optional) receives
 * the status message, NUL-terminated, truncated to cap. */
int tpr_call_finish(tpr_call *c, char *details, size_t cap);

/* Cancel: RST the stream. Safe at any point before finish. */
void tpr_call_cancel(tpr_call *c);

/* Destroy a finished/cancelled call object. */
void tpr_call_destroy(tpr_call *c);

void tpr_buf_free(uint8_t *data);

/* Convenience: full unary round trip. Returns the status code; on TPR_OK,
 * *resp/*resp_len carry the response (caller frees). */
int tpr_unary_call(tpr_channel *ch, const char *method, const uint8_t *req,
                   size_t req_len, uint8_t **resp, size_t *resp_len,
                   char *details, size_t details_cap, int timeout_ms);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* TPURPC_CLIENT_H */
