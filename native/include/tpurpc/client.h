/* tpurpc C client API — the app-facing native surface (SURVEY.md §1 L7).
 *
 * The reference ships a full C++ application API (src/cpp/ + include/grpcpp/,
 * 14,328 LoC) above its C core surface (src/core/lib/surface/). tpurpc's
 * equivalent is deliberately small: a blocking C API over the tpurpc native
 * framing (tpurpc/rpc/frame.py documents the wire format), speaking TCP to
 * any tpurpc server — including ring-platform and TPU-platform listeners,
 * whose accept loops protocol-sniff the preface (tpurpc/rpc/server.py).
 * A header-only C++ RAII wrapper lives in tpurpc/client.hpp.
 *
 * Concurrency model: one background reader thread per channel demuxes frames
 * to calls (the moral equivalent of grpc's completion-queue plumbing,
 * completion_queue.cc:393, collapsed to blocking calls); any number of app
 * threads may run calls on one channel concurrently.
 *
 * All functions return 0 / a valid pointer on success unless noted.
 * Status codes match gRPC's numbering (tpurpc/rpc/status.py).
 */
#ifndef TPURPC_CLIENT_H
#define TPURPC_CLIENT_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tpr_channel tpr_channel;
typedef struct tpr_call tpr_call;

/* -- status codes (grpc numbering) -- */
enum {
  TPR_OK = 0,
  TPR_CANCELLED = 1,
  TPR_UNKNOWN = 2,
  TPR_DEADLINE_EXCEEDED = 4,
  TPR_UNIMPLEMENTED = 12,
  TPR_INTERNAL = 13,
  TPR_UNAVAILABLE = 14
};

/* Connect a channel. timeout_ms bounds the TCP connect. NULL on failure.
 *
 * TPURPC_NATIVE_INLINE_READ=1 (ring platforms only): the lowest-latency
 * blocking discipline — no reader thread; the thread waiting in recv/
 * finish/ping pumps the transport itself (the reference's pollset_work
 * model), saving a thread wakeup per round trip. Deadlines are enforced
 * at frame boundaries. CQ async ops need the reader thread and return
 * NULL on such channels. Trade-off: with NO call in flight nothing reads
 * the transport, so an idle inline channel does not answer server
 * keepalive PINGs or observe GOAWAY until the next call — pair it with
 * call-per-connection or always-busy usage, not server-side keepalive
 * reaping. */
tpr_channel *tpr_channel_create(const char *host, int port, int timeout_ms);

/* Flag-taking variant. TPR_CHANNEL_INLINE_READ selects the inline-read
 * discipline explicitly (per channel, overriding the
 * TPURPC_NATIVE_INLINE_READ env default): blocking callers pump the
 * transport themselves — the lowest-latency discipline on ring
 * platforms (no reader-thread wakeup per RTT), at the price of the CQ
 * async API refusing on such channels (it needs the reader thread).
 * Ignored on TCP transports (a blocking fd read can't be caller-pumped
 * across concurrent streams). */
#define TPR_CHANNEL_INLINE_READ 1
tpr_channel *tpr_channel_create2(const char *host, int port, int timeout_ms,
                                 int flags);
void tpr_channel_destroy(tpr_channel *ch);

/* Round-trip a PING frame; returns microseconds, or -1 on failure. */
int64_t tpr_channel_ping(tpr_channel *ch, int timeout_ms);

/* Start a call. metadata: flat array of 2*n_md C strings (k,v,k,v,...);
 * timeout_ms <= 0 means no deadline. NULL when the channel is dead or the
 * server sent GOAWAY (max_connection_age drain) — in-flight calls still
 * complete, but new calls need a fresh tpr_channel_create. */
tpr_call *tpr_call_start(tpr_channel *ch, const char *method,
                         const char *const *metadata, size_t n_md,
                         int timeout_ms);

/* Send one request message. end_stream half-closes after this message. */
int tpr_call_send(tpr_call *c, const uint8_t *data, size_t len,
                  int end_stream);

/* Half-close without a message (client finished sending). */
int tpr_call_writes_done(tpr_call *c);

/* Receive the next response message. Returns 1 with *data/*len set (caller
 * frees with tpr_buf_free), 0 at end of the response stream (trailers seen),
 * -1 on transport error / deadline. */
int tpr_call_recv(tpr_call *c, uint8_t **data, size_t *len);

/* Block until trailers; returns the status code. details (optional) receives
 * the status message, NUL-terminated, truncated to cap. */
int tpr_call_finish(tpr_call *c, char *details, size_t cap);

/* Zero-copy send lease (ring transports only) — the reference's
 * SendZerocopy shape (pair.cc:793-941) for a shm ring: reserve `len`
 * payload bytes of ONE message directly in the transport ring, so the
 * producer serializes in place and the staging-buffer memcpy disappears.
 * On 0, the frame header is already written and (p1,l1)(+(p2,l2) at a
 * ring wrap) are the payload span to fill; then call
 * tpr_call_send_commit (publish + notify) or tpr_call_send_abort
 * (release without publishing). The channel's send path is LOCKED from a
 * successful reserve until commit/abort: commit promptly, same thread,
 * no other sends in between. -1 = not eligible (no ring, len 0 or over
 * one frame, channel dead, lease already held) — use tpr_call_send. */
int tpr_call_send_reserve(tpr_call *c, size_t len, int end_stream,
                          uint8_t **p1, size_t *l1,
                          uint8_t **p2, size_t *l2);

/* Fragment-aware reserve: flags is a bitmask. TPR_RESERVE_MORE marks this
 * frame as a non-final fragment of one logical message (the peer keeps
 * accumulating until a frame without it), letting a producer gather a
 * message LARGER than one frame through several reserve/commit leases.
 * TPR_RESERVE_END_STREAM half-closes after the final fragment. */
#define TPR_RESERVE_END_STREAM 1
#define TPR_RESERVE_MORE 2
int tpr_call_send_reserve2(tpr_call *c, size_t len, int flags,
                           uint8_t **p1, size_t *l1,
                           uint8_t **p2, size_t *l2);
int tpr_call_send_commit(tpr_call *c);
int tpr_call_send_abort(tpr_call *c);

/* Cancel: RST the stream. Safe at any point before finish. */
void tpr_call_cancel(tpr_call *c);

/* Destroy a finished/cancelled call object. */
void tpr_call_destroy(tpr_call *c);

void tpr_buf_free(uint8_t *data);

/* Convenience: full unary round trip. Returns the status code; on TPR_OK,
 * *resp/*resp_len carry the response (caller frees). */
int tpr_unary_call(tpr_channel *ch, const char *method, const uint8_t *req,
                   size_t req_len, uint8_t **resp, size_t *resp_len,
                   char *details, size_t details_cap, int timeout_ms);

/* Like tpr_unary_call, plus a machine-readable replay-safety verdict:
 * *preexec is set to 1 iff the failure provably happened BEFORE the complete
 * request could have reached a server handler (admission refusal on a
 * dead/draining channel, or a request-send failure that left END_STREAM
 * unsent), and 0 otherwise — including every failure after the request was
 * fully shipped, where a handler MAY have executed and a caller replay would
 * double-execute. Callers deciding whether to transparently retry MUST use
 * this flag, never the human-readable details text (tpurpc/rpc/channel.py
 * _native_call consumes it as RpcError._tpurpc_preexec). */
int tpr_unary_call_ex(tpr_channel *ch, const char *method, const uint8_t *req,
                      size_t req_len, uint8_t **resp, size_t *resp_len,
                      char *details, size_t details_cap, int timeout_ms,
                      int *preexec);

/* ---------------------------------------------------------------------------
 * Completion-queue async API — the reference's CQ-based async client shape
 * (grpc_completion_queue_next, completion_queue.cc:393; CompletionQueue::Next
 * in include/grpcpp/). Ops are tagged; completions surface as events pulled
 * by any number of app threads via tpr_cq_next. Sends remain direct calls
 * (they complete into the kernel/ring buffer synchronously; the blocking is
 * bounded by transport backpressure, as in the reference's write path) —
 * receive/finish, the genuinely asynchronous halves, are tag-driven.
 *
 * Deadlines on CQ calls are enforced lazily inside tpr_cq_next (the thread
 * pulling events doubles as the timer thread, like grpc's cq-driven timer
 * checks): an expired call is RST'd and its pending ops complete with
 * TPR_DEADLINE_EXCEEDED.
 */

typedef struct tpr_cq tpr_cq;

enum {
  TPR_EV_SHUTDOWN = 0, /* queue shut down and drained */
  TPR_EV_RECV = 1,     /* a tpr_call_recv_cq op completed */
  TPR_EV_FINISH = 2,   /* a tpr_call_finish_cq / tpr_unary_call_cq completed */
};

typedef struct {
  int type;      /* TPR_EV_* */
  void *tag;     /* the tag passed when the op was started */
  int ok;        /* RECV: 1 = data/len hold a message (caller frees),
                  *       0 = end of response stream (no message).
                  * FINISH: always 1. */
  uint8_t *data; /* RECV with ok=1, or unary FINISH response; else NULL */
  size_t len;
  int status;         /* FINISH: gRPC status code */
  char details[256];  /* FINISH: status details, NUL-terminated */
} tpr_event;

tpr_cq *tpr_cq_create(void);

/* Pull the next completion. Returns 1 and fills *ev on an event; 0 on
 * timeout (timeout_ms <= 0 means wait forever); -1 when the queue is shut
 * down and fully drained (ev->type = TPR_EV_SHUTDOWN). */
int tpr_cq_next(tpr_cq *cq, tpr_event *ev, int timeout_ms);

/* Begin shutdown: wakes waiters; tpr_cq_next keeps returning queued events
 * until drained, then -1. New ops on the queue are refused (best-effort:
 * as in grpc, STARTING an op concurrently with shutdown is undefined —
 * the app must stop issuing ops before calling shutdown, and must not
 * destroy the queue while an op-arming call is still executing). */
void tpr_cq_shutdown(tpr_cq *cq);

/* Destroy a shut-down queue. Undelivered RECV payloads are freed. All
 * calls started against this queue must be destroyed BEFORE the queue
 * (tpr_call_destroy unhooks the call from the queue's deadline scan). */
void tpr_cq_destroy(tpr_cq *cq);

/* Start a call whose recv/finish ops complete on `cq`. Same semantics as
 * tpr_call_start otherwise. Sends use the normal tpr_call_send /
 * tpr_call_writes_done. */
tpr_call *tpr_call_start_cq(tpr_channel *ch, const char *method,
                            const char *const *metadata, size_t n_md,
                            int timeout_ms, tpr_cq *cq);

/* Request the next response message; completes as a TPR_EV_RECV event.
 * Multiple outstanding recv ops on one call complete in order. Returns 0,
 * or -1 if the call is not a CQ call. */
int tpr_call_recv_cq(tpr_call *c, void *tag);

/* Request the terminal status; completes as TPR_EV_FINISH once trailers
 * (or a local terminal condition) arrive. At most one per call. */
int tpr_call_finish_cq(tpr_call *c, void *tag);

/* Async unary: small requests ship HEADERS+request in one buffered write
 * (large ones fragment); ONE TPR_EV_FINISH completion carries response
 * bytes (ok path) AND status — the reference's
 * AsyncResponseReader::Finish(response, status, tag) shape.
 * Returns the call (destroy after the completion) or NULL on refusal. */
tpr_call *tpr_unary_call_cq(tpr_channel *ch, const char *method,
                            const uint8_t *req, size_t req_len,
                            int timeout_ms, tpr_cq *cq, void *tag);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* TPURPC_CLIENT_H */
