// tpurpc C++ application API — RAII wrapper over the C client (client.h).
//
// The shape intentionally mirrors the reference's C++ surface
// (include/grpcpp/: grpc::CreateChannel / Stub / ClientReaderWriter) at the
// scale tpurpc needs: blocking calls, raw-bytes payloads (serialize with
// protobuf or tpurpc codegen above this layer).
//
//   tpurpc::Channel ch("127.0.0.1", 50051);
//   auto [status, reply] = ch.UnaryCall("/pkg.Svc/Method", request_bytes);
//   if (status.ok()) use(reply);
//
//   tpurpc::ClientCall call = ch.StartCall("/pkg.Svc/Chat");
//   call.Write("hello");
//   call.WritesDone();
//   std::string msg;
//   while (call.Read(&msg)) consume(msg);
//   tpurpc::Status st = call.Finish();
#ifndef TPURPC_CLIENT_HPP
#define TPURPC_CLIENT_HPP

#include <cstdint>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "client.h"

namespace tpurpc {

struct Status {
  int code = TPR_OK;
  std::string details;
  bool ok() const { return code == TPR_OK; }
};

class ClientCall {
 public:
  ClientCall(ClientCall &&o) noexcept : call_(o.call_) { o.call_ = nullptr; }
  ClientCall &operator=(ClientCall &&o) noexcept {
    if (call_) tpr_call_destroy(call_);
    call_ = o.call_;
    o.call_ = nullptr;
    return *this;
  }
  ClientCall(const ClientCall &) = delete;
  ClientCall &operator=(const ClientCall &) = delete;
  ~ClientCall() {
    if (call_) tpr_call_destroy(call_);
  }

  bool Write(const std::string &msg, bool end_stream = false) {
    return tpr_call_send(call_,
                         reinterpret_cast<const uint8_t *>(msg.data()),
                         msg.size(), end_stream ? 1 : 0) == 0;
  }
  bool WritesDone() { return tpr_call_writes_done(call_) == 0; }

  // Blocking read; false at end-of-stream or error (Finish() tells which).
  bool Read(std::string *out) {
    uint8_t *data = nullptr;
    size_t len = 0;
    int r = tpr_call_recv(call_, &data, &len);
    if (r != 1) return false;
    out->assign(reinterpret_cast<char *>(data), len);
    tpr_buf_free(data);
    return true;
  }

  Status Finish() {
    char buf[1024];
    Status st;
    st.code = tpr_call_finish(call_, buf, sizeof buf);
    st.details = buf;
    return st;
  }

  void Cancel() { tpr_call_cancel(call_); }

 private:
  friend class Channel;
  explicit ClientCall(tpr_call *c) : call_(c) {}
  tpr_call *call_;
};

class Channel {
 public:
  Channel(const std::string &host, int port, int connect_timeout_ms = 10000)
      : ch_(tpr_channel_create(host.c_str(), port, connect_timeout_ms)) {
    if (!ch_) throw std::runtime_error("tpurpc: connect failed");
  }
  ~Channel() {
    if (ch_) tpr_channel_destroy(ch_);
  }
  Channel(const Channel &) = delete;
  Channel &operator=(const Channel &) = delete;

  // Round-trip latency in microseconds; throws on a dead channel.
  int64_t PingUs(int timeout_ms = 5000) {
    int64_t us = tpr_channel_ping(ch_, timeout_ms);
    if (us < 0) throw std::runtime_error("tpurpc: ping failed");
    return us;
  }

  ClientCall StartCall(
      const std::string &method,
      const std::vector<std::pair<std::string, std::string>> &metadata = {},
      int timeout_ms = 0) {
    std::vector<const char *> flat;
    flat.reserve(metadata.size() * 2);
    for (const auto &kv : metadata) {
      flat.push_back(kv.first.c_str());
      flat.push_back(kv.second.c_str());
    }
    tpr_call *c = tpr_call_start(ch_, method.c_str(),
                                 flat.empty() ? nullptr : flat.data(),
                                 metadata.size(), timeout_ms);
    if (!c) throw std::runtime_error("tpurpc: call start failed");
    return ClientCall(c);
  }

  std::pair<Status, std::string> UnaryCall(const std::string &method,
                                           const std::string &request,
                                           int timeout_ms = 0) {
    uint8_t *resp = nullptr;
    size_t resp_len = 0;
    char details[1024] = {0};
    Status st;
    st.code = tpr_unary_call(
        ch_, method.c_str(), reinterpret_cast<const uint8_t *>(request.data()),
        request.size(), &resp, &resp_len, details, sizeof details, timeout_ms);
    st.details = details;
    std::string body;
    if (st.ok() && resp) {
      body.assign(reinterpret_cast<char *>(resp), resp_len);
      tpr_buf_free(resp);
    }
    return {st, body};
  }

 private:
  tpr_channel *ch_;
};

}  // namespace tpurpc

#endif  // TPURPC_CLIENT_HPP
