/* tpurpc C server API — native app servers over the tpurpc framing.
 *
 * Counterpart of client.h; together they are the app-facing native surface
 * the reference provides as src/cpp/server (ServerBuilder / sync service,
 * SURVEY.md §1 L7). Scope: blocking handlers, all four call shapes
 * expressed through one call object (read-until-end / write-many /
 * finish-with-status).
 *
 * Threading (round 4, the reference Poller model — ibverbs/poller.cc:52-106,
 * capacity 4096 pairs over N threads): connections are MULTIPLEXED over a
 * small fixed set of poller threads (TPURPC_SERVER_POLLERS /
 * GRPC_RDMA_POLLER_THREAD_NUM, default 1) that epoll every connection's
 * event fd and parse frames incrementally — NOT a thread per connection,
 * so the server holds hundreds of concurrent ring/TCP connections with
 * bounded threads. Callback-API (`tpr_server_register_callback`) handlers
 * run inline on the poller thread; handler-API (`tpr_server_register`)
 * calls still get a dedicated thread each (they block in tpr_srv_recv).
 */
#ifndef TPURPC_SERVER_H
#define TPURPC_SERVER_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tpr_server tpr_server;
typedef struct tpr_server_call tpr_server_call;

/* Handler: drive the call via tpr_srv_recv/tpr_srv_send, then return the
 * status code to send in trailers (0 = OK). `ud` is the registration's
 * user data pointer. */
typedef int (*tpr_handler_fn)(tpr_server_call *call, void *ud);

/* Create a server bound to 127.0.0.1:port (port 0 = ephemeral; actual port
 * via tpr_server_port). NULL on bind failure. */
tpr_server *tpr_server_create(int port);
int tpr_server_port(tpr_server *s);

/* Register a handler for an exact :path. Must precede tpr_server_start. */
void tpr_server_register(tpr_server *s, const char *method, tpr_handler_fn fn,
                         void *ud);

/* -- callback (reactor) API --------------------------------------------
 *
 * The reference ships sync, CQ-async, AND callback server APIs
 * (src/cpp/server/server_callback.cc); this is tpurpc's callback shape.
 * `on_msg` fires ON THE CONNECTION READER THREAD once per complete request
 * message — no per-call thread, no handoff: the low-latency path for
 * message-echo/transform services. Reply synchronously with tpr_srv_send.
 * Contract: return 0 to continue; a positive return ends the call NOW with
 * that status code (negative returns are coerced to INTERNAL(13) — the
 * client always gets trailers). At client half-close the call ends OK.
 * Handlers must not block: they stall every stream on the connection
 * (exactly like gRPC callback reactors). */
typedef int (*tpr_msg_cb)(tpr_server_call *call, const uint8_t *data,
                          size_t len, void *ud);
void tpr_server_register_callback(tpr_server *s, const char *method,
                                  tpr_msg_cb on_msg, void *ud);

/* Fallback handler for methods with no exact registration (runs on its own
 * thread, like tpr_server_register handlers). The seam a language-level
 * server uses for DYNAMIC method resolution (grpcio generic handlers):
 * the trampoline looks the path up in the language registry per call.
 * Without a default, unknown methods get UNIMPLEMENTED trailers. */
void tpr_server_register_default(tpr_server *s, tpr_handler_fn fn, void *ud);

/* Start the accept loop (background thread). */
int tpr_server_start(tpr_server *s);

/* Stop accepting, close connections, join threads, free. */
void tpr_server_destroy(tpr_server *s);

/* Adopt an ALREADY-ACCEPTED connected socket: the server takes ownership
 * of `fd`, sniffs the protocol (ring bootstrap magic vs framing preface)
 * and serves it exactly like an accepted connection. `preread` replays
 * bytes the caller already consumed from the socket (<= 4; pass NULL/0
 * when the caller peeked instead). This is the seam a language-level
 * server (tpurpc/rpc/server.py) uses to put its accepted connections on
 * the native data plane. Requires tpr_server_start to have run. Returns
 * 0 on success, -1 on refusal (server stopping / preread too long). */
int tpr_server_adopt_fd(tpr_server *s, int fd, const uint8_t *preread,
                        size_t preread_len);

/* -- inside a handler -- */

/* Next request message: 1 = got one (*data/*len set, free with
 * tpr_srv_buf_free), 0 = client half-closed, -1 = connection error/cancel. */
int tpr_srv_recv(tpr_server_call *c, uint8_t **data, size_t *len);

/* Send one response message. */
int tpr_srv_send(tpr_server_call *c, const uint8_t *data, size_t len);

/* The call's :path (valid for the handler's duration). */
const char *tpr_srv_method(tpr_server_call *c);

/* Remaining time before the client's deadline, in microseconds;
 * INT64_MAX when the call has no deadline. */
int64_t tpr_srv_deadline_us(tpr_server_call *c);

/* Set the trailers' :message detail (optional, before returning). */
void tpr_srv_set_details(tpr_server_call *c, const char *details);

/* Request metadata (every header the client sent except :path/:timeout-us).
 * Pointers are valid for the handler's duration. */
size_t tpr_srv_metadata_count(tpr_server_call *c);
int tpr_srv_metadata_get(tpr_server_call *c, size_t i, const char **key,
                         const char **val);

/* Queue initial metadata (sent as a HEADERS frame before the first
 * response message; no-op after the first send). */
void tpr_srv_send_initial_md(tpr_server_call *c, const char *key,
                             const char *val);

/* Add a custom trailing-metadata pair to the final trailers. */
void tpr_srv_add_trailing_md(tpr_server_call *c, const char *key,
                             const char *val);

/* 1 when the client cancelled (RST) or the connection died. */
int tpr_srv_cancelled(tpr_server_call *c);

void tpr_srv_buf_free(uint8_t *data);

#ifdef __cplusplus
}
#endif

#endif /* TPURPC_SERVER_H */
