// Typed (protobuf) wrappers over the tpurpc native call objects — the
// runtime support header for code the tpurpc protoc plugin generates with
// --tpurpc_out=cpp:DIR (see tpurpc/codegen/plugin.py). The reference's
// analog is the grpc++ codegen support layer (include/grpcpp/impl/codegen/)
// under stubs emitted by src/compiler/cpp_generator.cc.
//
// Message types must provide protobuf's SerializeAsString / ParseFromArray
// (any google::protobuf::MessageLite does).
#ifndef TPURPC_TYPED_HPP
#define TPURPC_TYPED_HPP

#include <string>
#include <utility>

#include "client.hpp"
#include "server.h"

namespace tpurpc {

// Client side: a typed view of a streaming call. W = request message type,
// R = response message type.
template <typename W, typename R>
class TypedCall {
 public:
  explicit TypedCall(ClientCall &&c) : call_(std::move(c)) {}

  bool Write(const W &msg, bool end_stream = false) {
    return call_.Write(msg.SerializeAsString(), end_stream);
  }
  bool WritesDone() { return call_.WritesDone(); }

  // Blocking typed read; false at end-of-stream, error, or parse failure
  // (Finish() distinguishes; a parse failure sets parse_error()).
  bool Read(R *out) {
    std::string raw;
    if (!call_.Read(&raw)) return false;
    if (!out->ParseFromArray(raw.data(), static_cast<int>(raw.size()))) {
      parse_error_ = true;
      return false;
    }
    return true;
  }

  Status Finish() {
    Status st = call_.Finish();
    if (st.ok() && parse_error_) {
      st.code = TPR_INTERNAL;
      st.details = "response message parse failed";
    }
    return st;
  }
  void Cancel() { call_.Cancel(); }
  bool parse_error() const { return parse_error_; }

 private:
  ClientCall call_;
  bool parse_error_ = false;
};

// Server side: a typed view of the handler's call object. R = request
// message type (Read), W = response message type (Write).
template <typename R, typename W>
class ServerCall {
 public:
  explicit ServerCall(tpr_server_call *c) : c_(c) {}

  // Next request message; false at client half-close / cancel / bad parse.
  bool Read(R *out) {
    uint8_t *data = nullptr;
    size_t len = 0;
    if (tpr_srv_recv(c_, &data, &len) != 1) return false;
    bool ok = out->ParseFromArray(data, static_cast<int>(len));
    tpr_srv_buf_free(data);
    if (!ok) parse_error_ = true;
    return ok;
  }

  bool Write(const W &msg) {
    std::string raw = msg.SerializeAsString();
    return tpr_srv_send(c_, reinterpret_cast<const uint8_t *>(raw.data()),
                        raw.size()) == 0;
  }

  void SetDetails(const std::string &d) { tpr_srv_set_details(c_, d.c_str()); }
  int64_t DeadlineRemainingUs() const { return tpr_srv_deadline_us(c_); }
  bool parse_error() const { return parse_error_; }
  tpr_server_call *raw() { return c_; }

 private:
  tpr_server_call *c_;
  bool parse_error_ = false;
};

}  // namespace tpurpc

#endif  // TPURPC_TYPED_HPP
