// Native rendezvous + ctrl-ring plane (ROADMAP item 6): the C loop's side
// of the OFFER/CLAIM/COMPLETE/RELEASE zero-copy bulk ladder and the 128 B
// descriptor ctrl rings. The AUTHORITATIVE protocol lives in
// tpurpc/core/rendezvous.py and tpurpc/core/ctrlring.py — every struct
// layout, constant and ordering rule here is a byte-exact mirror of those
// two files (cross-plane interop is the acceptance bar; see
// ARCHITECTURE.md §27 for the shared layouts and the load/store contract).
//
// One tpr_rdv::Link hangs off each framed connection (client channel or
// adopted server conn) and carries BOTH roles:
//
//  - sender: eligible payloads (>= TPURPC_RENDEZVOUS_MIN_KB, negotiated
//    link) OFFER, wait for the peer's CLAIM (or reuse a STANDING grant on
//    its doorbell word — RDMAbox's pre-registered-buffer discipline,
//    arXiv:2104.12197), memcpy into the claimed shm window, COMPLETE.
//    Every failure returns false and the caller sends framed — fallback,
//    never a hang.
//  - receiver: OFFERs lease landing regions from a process-wide shm pool,
//    CLAIMs advertise them, COMPLETEs deliver the region bytes zero-copy
//    through the conn's OwnedBuf path; tpr_rdv::settle() is the single
//    "consumer is done with the pointer" entry (tpr_srv_buf_free and the
//    OwnedBuf destructor both route region pointers here).
//
// Control ops prefer the peer's ctrl ring (CtrlTx) and fall back framed;
// our own receive ring (CtrlRx) is drained by the conn's dispatch thread
// with the stamp-acquire / cons_head-release / parked-seqcst ordering
// documented at the member functions.
#ifndef TPURPC_TPR_RDV_H
#define TPURPC_TPR_RDV_H

#include <stdint.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "ring_transport.h"

namespace tpr_rdv {

// canonical control ops (rendezvous.py OP_*); frame type = op + 7
constexpr uint8_t kOpOffer = 1, kOpClaim = 2, kOpComplete = 3,
                  kOpRelease = 4;
// PING payload prefix that negotiates the ladder (rendezvous.py
// HELLO_PAYLOAD); the ctrl-ring descriptor blob rides behind it
constexpr char kHelloPayload[] = "\x00tpurpc-rdv1";
constexpr size_t kHelloPayloadLen = 12;

constexpr uint64_t kMinClass = 64 * 1024;      // _MIN_CLASS
constexpr uint64_t kMaxTransfer = 1ull << 30;  // _MAX_TRANSFER
constexpr size_t kNonceBytes = 16;
constexpr int kPregrantDepth = 4;              // _PREGRANT_DEPTH

// ctrl ring layout (ctrlring.py): 64 B header + nslots * 128 B slots
constexpr uint32_t kCtrlMagic = 0x54504352;  // 'TPCR'
constexpr uint32_t kCtrlVersion = 1;
constexpr uint32_t kCtrlSlotBytes = 128;
constexpr uint32_t kCtrlHdrBytes = 64;
constexpr uint32_t kCtrlSlotHdrBytes = 24;  // stamp, frame_seq, sid, len, op
constexpr uint32_t kMaxCtrlPayload = kCtrlSlotBytes - kCtrlSlotHdrBytes;
constexpr size_t kConsHeadOff = 16;
constexpr size_t kParkedOff = 24;
constexpr size_t kCtrlNonceOff = 32;

// -- env gates (read live, same knobs as the Python plane) -------------------
bool enabled();                 // TPURPC_RENDEZVOUS (default on)
uint64_t min_bytes();           // TPURPC_RENDEZVOUS_MIN_KB (default 256) KiB
uint64_t pool_budget();         // TPURPC_RENDEZVOUS_POOL_MB (default 256) MiB
double claim_timeout_s();       // TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S (5)
bool ctrl_enabled();            // TPURPC_CTRL_RING (default on)
uint32_t ctrl_slots();          // TPURPC_CTRL_RING_SLOTS (default 64, min 8)
uint64_t size_class(uint64_t nbytes);  // pow2 >= nbytes, floor 64 KiB

// -- process-global counters (the ledger the shim/tests read) ----------------
// Indices are ABI for tpr_rdv_counters (native_client.py binds them).
enum CounterIdx {
  kCtrRdvSent = 0,       // sender: messages moved via rendezvous
  kCtrRdvRecv,           // receiver: messages delivered from a region
  kCtrRdvFallback,       // sender: eligible messages that fell back framed
  kCtrRdvBytesSent,      // sender: one-sided bytes placed (the rdma_write)
  kCtrRdvBytesRecv,      // receiver: region bytes delivered
  kCtrRdvRefused,        // receiver: offers refused (budget/limit)
  kCtrCtrlPosts,         // producer: records placed in the peer's ring
  kCtrCtrlKicks,         // producer: framed kicks sent (parked consumer)
  kCtrCtrlRecords,       // consumer: records drained from our ring
  kCtrCtrlFrames,        // control ops that went FRAMED (ring miss/cold)
  kCtrHostCopyBytes,     // framed kMessage payload bytes on negotiated conns
  kCtrPregrants,         // receiver: standing pre-grants issued
  kNumCounters,
};
extern std::atomic<uint64_t> g_counters[kNumCounters];
inline void count(CounterIdx i, uint64_t n = 1) {
  g_counters[i].fetch_add(n, std::memory_order_relaxed);
}

// -- settle registry ---------------------------------------------------------
// A delivered region pointer must be settled EXACTLY once when its last
// consumer is done. Returns true when ptr was a registered rdv delivery
// (handled: doorbell rung / region recycled); false means the pointer is a
// plain malloc buffer and the caller should free() it.
bool settle(const void *ptr);
// True if ptr is a live rdv delivery (OwnedBuf adoption asks before free).
bool is_delivery(const void *ptr);

struct Lease;   // receiver-side region lease (tpr_rdv.cc)
struct Claim;   // sender-side view of a peer's claim (tpr_rdv.cc)

// -- the per-connection link -------------------------------------------------
class Link {
 public:
  explicit Link(const char *name);
  ~Link();

  // Wiring the owning connection provides before any traffic flows.
  // send_frame queues ONE framed control frame (types 8..12) on the
  // connection (under its write lock, bumping frames_sent); deliver hands
  // a completed rdv payload to the stream layer — `data` points into the
  // landing region and MUST be settle()d exactly once; wake pokes the
  // conn-level cv so claim waiters parked on it re-check.
  std::function<bool(uint8_t type, uint32_t sid, const std::string &p)>
      send_frame;
  std::function<void(uint32_t sid, uint8_t flags, uint8_t *data,
                     size_t len)> deliver;
  std::function<void()> wake;
  // Optional claim-wait pump for inline-read transports (no reader
  // thread): run the conn's frame pump until pred() or the deadline.
  std::function<void(const std::function<bool()> &pred,
                     std::chrono::steady_clock::time_point dl)> pump;

  // Frame accounting for the ctrl-ring ordering gate: the conn bumps
  // frames_sent for EVERY frame it queues (the producer stamps it into
  // posted records) and frames_dispatched for every frame it dispatches
  // (our consumer leaves a record in place until the frames it must order
  // after have been dispatched). Both count ALL frame types.
  std::atomic<uint64_t> frames_sent{0};
  std::atomic<uint64_t> frames_dispatched{0};

  std::atomic<bool> negotiated{false};

  // -- negotiation -----------------------------------------------------------
  // The hello PING payload this side sends right after the preface:
  // HELLO_PAYLOAD + our receive ring's descriptor blob (empty blob when
  // ctrl rings are off or shm is unavailable).
  std::string hello_payload();
  // Called for every received PING. True when the payload was a capability
  // hello (the caller still echoes the PONG): arms rendezvous and opens
  // the peer's ctrl ring from the trailing blob.
  bool maybe_hello(const uint8_t *payload, size_t len);

  // -- dispatch --------------------------------------------------------------
  // Frame types 8..12 from the conn's frame loop. Returns true when the
  // frame was a control frame this link consumed. Never throws; malformed
  // control payloads degrade to refused/ignored transfers.
  bool on_frame(uint8_t type, uint32_t sid, const uint8_t *p, size_t len);
  void on_op(uint8_t op, uint32_t sid, const uint8_t *p, size_t len);

  // -- sender role -----------------------------------------------------------
  // The frame-dispatch thread must never block on a claim (the claim's
  // own delivery runs there): the conn records it once known.
  void set_dispatch_thread();
  bool eligible(size_t total) const;
  // Move one whole MESSAGE payload via rendezvous. True = placed and
  // COMPLETE sent (the framed path must NOT also send it); false = fall
  // back framed (refused, timeout, write failure) — never an exception,
  // never a hang.
  bool send_message(uint32_t sid, uint8_t flags, const uint8_t *data,
                    size_t total);

  // -- ctrl-ring consumer face ----------------------------------------------
  bool ctrl_armed() const { return ctrl_tx_open_.load(); }
  bool ctrl_rx_ready() const { return rx_inited_; }
  // Drain every ready record in one pass (one cons_head publish per
  // batch); records gated on frames_dispatched stay in place. Safe from
  // any thread (try-lock; concurrent drainers skip). Updates the hot/cold
  // EWMA: hits heat, empty probes decay.
  int ctrl_drain();
  // The drain-EWMA hot/cold discipline (read_frame_polled's): hot conns
  // keep polling the ring off short fd-poll slices; a cold consumer parks
  // (parked=1, then ONE mandatory re-drain closes the lost-wakeup race —
  // the producer reads parked strictly after its stamp store).
  bool ctrl_hot();
  void ctrl_park();
  void ctrl_decay();  // one empty poll slice: miss-decay the EWMA

  // -- lifecycle -------------------------------------------------------------
  // Connection death: discard-quarantine every claimed region (a
  // straggling peer window must land in orphaned memory, never a region
  // re-leased to a new transfer), wake every claim waiter, close rings.
  void close();
  bool is_closed() const { return closed_.load(); }

 private:
  friend struct Lease;
  // control send: ring first (when armed and ring_ok), framed fallback
  void ctrl_send(uint8_t op, uint32_t sid, const std::string &payload,
                 bool ring_ok = true);
  void ctrl_kick();

  // sender internals
  std::shared_ptr<Claim> take_grant(uint64_t cls, size_t total);
  bool has_standing(uint64_t cls, size_t total);
  bool standing_free(const std::shared_ptr<Claim> &c);
  void drop_grant(const std::shared_ptr<Claim> &c);
  std::shared_ptr<Claim> rdv_claim(uint32_t sid, size_t total, uint64_t cls);
  uint8_t *window_base(const std::string &handle, size_t nbytes);
  // Window pin: raw window pointers escape mu_ for the bulk memcpy and
  // doorbell reads, so close() must not munmap while any pin is held.
  // pin_windows() orders the increment BEFORE the closed_ check (seq_cst
  // both sides): either the pinner sees closed_ and backs out, or close()
  // sees the pin and waits for it to drain before unmapping.
  bool pin_windows();
  void unpin_windows();
  bool rdv_write(const std::shared_ptr<Claim> &c, const uint8_t *data,
                 size_t total);
  void rdv_complete(const std::shared_ptr<Claim> &c, uint32_t sid,
                    uint8_t flags, size_t total);
  void rdv_release(const std::shared_ptr<Claim> &c);

  // receiver internals
  void on_offer(uint32_t sid, const uint8_t *p, size_t len);
  void on_claim(const uint8_t *p, size_t len);
  void on_complete(uint32_t sid, const uint8_t *p, size_t len);
  void on_release(const uint8_t *p, size_t len);
  void maybe_pregrant(uint64_t cls);

  std::string name_;
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::atomic<bool> closed_{false};
  // tpurpc-xray flight tags, interned ONCE at link construction (the
  // tpr-obs static-tag discipline); obs_adopted_ gates spin/park/stall
  // emission so the ctrl-ring machine never sees a flip before ADOPT
  uint16_t otag_rdv_ = 0, otag_ctrl_ = 0;
  std::atomic<bool> obs_adopted_{false};
  std::atomic<unsigned long> dispatch_tid_{0};
  std::atomic<int> window_pins_{0};  // senders inside a window deref

  // sender state (mu_)
  uint64_t next_req_ = 1;
  struct PendingReq {
    int state = 0;  // 0 pending, 1 claimed, 2 refused
    std::shared_ptr<Claim> claim;
  };
  std::unordered_map<uint64_t, std::shared_ptr<PendingReq>> reqs_;
  std::map<uint64_t, std::vector<std::shared_ptr<Claim>>> grants_;
  // open peer-region windows, keyed by handle. Never evicted before link
  // close: a mid-copy eviction would munmap under a writer, and the
  // peer's pool bounds the distinct handles one link can see.
  std::unordered_map<std::string, tpr_ring::ShmRegion> windows_;

  // receiver state (mu_)
  uint64_t next_lease_ = 1;
  std::unordered_map<uint64_t, std::shared_ptr<Lease>> leases_;
  std::unordered_map<uint64_t, uint64_t> req_lease_;
  std::map<uint64_t, int> pregrants_out_;

  // ctrl rings
  struct CtrlRx {
    tpr_ring::ShmRegion shm;
    uint32_t nslots = 0;
    uint64_t head = 0;
    uint8_t nonce[kNonceBytes];
  } rx_;
  bool rx_inited_ = false;
  std::mutex rx_mu_;  // drain try-lock
  struct CtrlTx {
    tpr_ring::ShmRegion shm;
    uint32_t nslots = 0;
    uint64_t seq = 0;
    bool stalled = false;  // ring-full edge
  } tx_;
  std::atomic<bool> ctrl_tx_open_{false};
  std::mutex tx_mu_;
  // consumer hot/cold EWMA (read_frame_polled's constants)
  std::mutex ewma_mu_;
  double ewma_ = 0.0;
  bool mode_hot_ = false;
};

}  // namespace tpr_rdv

#endif  // TPURPC_TPR_RDV_H
