// tpurpc-xray: the native plane's shared-memory observability surface.
//
// The C loop is the fastest plane and must not become the blindest one:
// this module gives it the SAME two instruments the Python plane already
// answers to — a flight recorder of transport EDGES (obs/flight.py's 32 B
// record shape, CLOCK_MONOTONIC stamps, interned entity tags) and a
// fixed-slot metrics table (the tpr_rdv_counters ledger generalized to
// counter/byte/busy_ns slots) — both living in ONE shm region so Python
// maps them with zero ctypes calls on the read path and the C writers pay
// zero syscalls and zero locks on the hot path.
//
// Region layout (all offsets little-endian, 64 B header):
//
//   [header 64 B]
//   [metrics   : kNumMetrics u64 atomic slots]
//   [tag table : tag_cap slots x kTagBytes (u16 len + name bytes)]
//   [seq words : capacity u64 atomic slots]
//   [records   : capacity x 32 B  (<Q t_ns><H code><H tag><I tid><q a1><q a2>)]
//
// Writer protocol (seqlock per slot, global order from one ticket word):
//   ticket = header.write_ticket.fetch_add(1, relaxed)
//   slot   = ticket % capacity
//   wait until seq[slot] == prior lap's stamp (claims the slot: a writer
//                                 lagging a FULL ring lap behind a
//                                 wrapping peer must not interleave)
//   seq[slot] = 0                (release: slot now in-progress)
//   record words stored relaxed  (4 x u64 — atomic words, never a memcpy,
//                                 so a racing reader is a detected torn
//                                 read, not UB)
//   seq[slot] = ticket + 1       (release: record whole and ordered)
//
// Reader protocol (Python's mmap decoder and tpr_obs_read both):
//   s1 = seq[slot] (acquire); skip if 0
//   copy the 4 words; s2 = seq[slot]; skip if s2 != s1
// A wrap during the copy moves seq by >= capacity, so the recheck catches
// it; ticket order (s1 - 1) is the global emission order.
//
// Event codes REUSE obs/flight.py's stable ints for every edge the Python
// plane also records (rdv offer/claim/write/complete/release, ctrl
// adopt/spin/park/stall, conn connect/dead) so the protocol machines in
// analysis/protocol.py replay the C plane UNMODIFIED; native-only edges
// (pin-wait, delivery-stall, rdv-fallback) take new appended codes.
//
// Emission discipline (the `tpr-obs` lint rule, analysis/lint.py): every
// site goes through TPR_OBS(kEv<Name>, <pre-interned tag>, a1, a2) — a
// static code constant, a tag interned ONCE at connect time (never
// tpr_obs::tag_for(...) in the call), pure integer args, no string
// literals. Events are edges, not traffic.
#ifndef TPURPC_TPR_OBS_H
#define TPURPC_TPR_OBS_H

#include <stdint.h>

namespace tpr_obs {

constexpr uint32_t kObsMagic = 0x54505258;  // 'TPRX'
constexpr uint32_t kObsVersion = 1;
constexpr uint32_t kRecordBytes = 32;
constexpr uint32_t kTagBytes = 48;  // u16 len + up to 46 name bytes
constexpr uint32_t kTagCap = 256;

// header field offsets (ABI for the Python decoder)
constexpr uint32_t kHdrMagic = 0;
constexpr uint32_t kHdrVersion = 4;
constexpr uint32_t kHdrCapacity = 8;
constexpr uint32_t kHdrTagCap = 12;
constexpr uint32_t kHdrMetricsCap = 16;
constexpr uint32_t kHdrRecordBytes = 20;
constexpr uint32_t kHdrTicket = 24;     // u64 atomic
constexpr uint32_t kHdrMetricsOff = 32;
constexpr uint32_t kHdrTagsOff = 36;
constexpr uint32_t kHdrSeqOff = 40;
constexpr uint32_t kHdrRecOff = 44;
constexpr uint32_t kHdrTagCount = 48;   // u32 atomic
constexpr uint32_t kHdrBytes = 64;

// -- event codes -------------------------------------------------------------
// Shared codes mirror tpurpc/obs/flight.py EXACTLY (append-only ABI there);
// native-only codes are appended past the Python plane's current tail and
// registered in flight.EVENT_NAMES by the same PR that adds them here.
enum EventCode : uint16_t {
  kEvPeerDeath = 15,
  kEvConnConnect = 17,
  kEvConnDead = 18,
  kEvRdvOffer = 33,
  kEvRdvClaim = 34,
  kEvRdvWrite = 35,
  kEvRdvComplete = 36,
  kEvRdvRelease = 37,
  kEvCtrlAdopt = 56,
  kEvCtrlSpin = 57,
  kEvCtrlPark = 58,
  kEvCtrlStallBegin = 59,
  kEvCtrlStallEnd = 60,
  // native-only (machine-free: protocol machines ignore unknown codes)
  kEvPinWaitBegin = 70,    // close() waiting on window pins; a1 = pins held
  kEvPinWaitEnd = 71,      // a1 = waited ns
  kEvDlvStallBegin = 72,   // delivery-shard backlog crossed high water; a1 = depth
  kEvDlvStallEnd = 73,     // backlog drained below low water; a1 = depth
  kEvRdvFallback = 74,     // eligible send fell back framed; a1 = bytes,
                           // a2 = reason (0 no claim, 1 write failed)
};

// -- metrics table -----------------------------------------------------------
// Fixed-slot ABI like tpr_rdv's CounterIdx: the INDEX is the contract
// (tpurpc/obs/native_obs.py mirrors these names in the same order and the
// registry scrapes them as native_* series). Append-only.
enum MetricIdx {
  kMetRdvSendBytes = 0,   // one-sided bytes placed by rdv_write
  kMetRdvSendBusyNs,      // ns inside the placement memcpy
  kMetRdvRecvBytes,       // region bytes delivered to the stream layer
  kMetRdvRecvBusyNs,      // ns inside deliver()
  kMetRdvWaitNs,          // ns senders spent waiting on solicited claims
  kMetRdvWaits,           // solicited claim waits begun
  kMetRdvFallbacks,       // eligible sends that fell back framed
  kMetCtrlDrainBatches,   // non-empty ctrl_drain passes
  kMetCtrlDrainRecords,   // records drained across those passes
  kMetCtrlKicks,          // framed kicks sent to a parked consumer
  kMetCtrlPosts,          // records placed in the peer's ring
  kMetCtrlFrames,         // control ops that went framed (ring miss/cold)
  kMetPinWaits,           // close() paths that found pins held
  kMetPinWaitNs,          // ns close() spent waiting for pins to drain
  kMetDlvEnqueued,        // delivery-shard items enqueued
  kMetDlvDrained,         // delivery-shard items delivered
  kMetDlvStalls,          // backlog high-water crossings
  kMetDlvDepth,           // gauge: current delivery backlog
  kMetConnUp,             // connections established (native plane)
  kMetConnDown,           // connections died
  kMetEmitted,            // flight records emitted (wraps overwrite)
  kMetTagOverflow,        // tag interns refused (table full -> tag 0)
  kNumMetrics,
};

// TPURPC_NATIVE_OBS=0 turns the whole plane off (read once at first use):
// emit/metric/tag_for become no-ops and no shm region is created. The
// tpr_rdv_counters ledger ABI is untouched either way.
bool enabled();

// Intern `name` to a small int once per entity lifetime (connect time).
// Returns 0 (the anonymous tag) on overflow or when the plane is off —
// never an error.
uint16_t tag_for(const char *name);

// The hot path: one ticket fetch_add + one acquire load (the slot claim,
// which only ever spins when a peer writer lags a full ring lap) + four
// relaxed word stores bracketed by two release stores. Never allocates,
// never takes a lock, never syscalls (clock_gettime is vDSO). No-op when
// the plane is off.
void emit(uint16_t code, uint16_t tag, int64_t a1, int64_t a2);

void metric_add(MetricIdx i, uint64_t n = 1);
void metric_store(MetricIdx i, uint64_t v);  // gauges
uint64_t metric_get(MetricIdx i);

uint64_t now_ns();  // CLOCK_MONOTONIC (== Python time.monotonic_ns())

}  // namespace tpr_obs

// The ONE emission spelling (the tpr-obs lint rule keys on it).
#define TPR_OBS(code, tag, a1, a2) \
  ::tpr_obs::emit((uint16_t)(code), (uint16_t)(tag), (int64_t)(a1), \
                  (int64_t)(a2))

// -- C ABI (tpurpc/obs/native_obs.py binds these) ----------------------------
extern "C" {
int tpr_obs_enabled(void);
// Forces lazy init; returns the shm object name (no leading slash, the
// Python SharedMemory convention -> /dev/shm/<name>) or "" when off.
const char *tpr_obs_shm_name(void);
uint32_t tpr_obs_layout_version(void);
uint32_t tpr_obs_capacity(void);
void tpr_obs_counters(uint64_t *out, int n);
// Seqlock-consistent snapshot of whole records (32 B each) into out;
// returns the record count. Torn/in-progress slots are skipped.
int tpr_obs_read(uint8_t *out, int max_records);
int tpr_obs_tag_name(uint32_t tag, char *out, int cap);
uint16_t tpr_obs_tag_for(const char *name);
void tpr_obs_emit(uint16_t code, uint16_t tag, int64_t a1, int64_t a2);
void tpr_obs_reset(void);
// Forked child: drop the inherited mapping (without unlinking the
// parent's region) and start a fresh one, so a shard's evidence is its
// own. Python's postfork hooks call this when the lib is loaded.
void tpr_obs_postfork(void);
}

#endif  // TPURPC_TPR_OBS_H
