// Ring transport for native (C/C++) tpurpc apps: the same one-sided-write
// shm data plane Python endpoints ride (tpurpc/core/pair.py), implemented
// against the C ring ops in ring.cc — so GRPC_PLATFORM_TYPE=RDMA_BP|BPEV|
// EVENT works for a pure-native process with no Python anywhere.
//
// Protocol parity (the authoritative impl is tpurpc/core/pair.py):
// - bootstrap: "TRB1" magic + u32 length + JSON Address blob each way over
//   the connected TCP fd (pair.py _send_blob/_recv_blob; the reference's
//   exchange_data, rdma_bp_posix.cc:640-692). Address keys: tag, domain,
//   ring_size, ring, status, caps. domain must be "shm" on both sides.
// - data: seq-stamped header/footer framed ring messages (ring.cc ops),
//   credits published as a one-sided u64 store of the consumer head into
//   the peer's status page (+0) after >= capacity/4 consumed
//   (RingReader.PUBLISH_DIVISOR), peer_exit at +8.
// - events: the bootstrap socket stays alive as the notify channel carrying
//   single-byte tokens 'd' (data), 'c' (credit), 'x' (exit). This side
//   advertises NO "waitflag" capability, so the Python peer always sends
//   notify bytes (the asymmetric-peer contract, pair.py Address.caps), and
//   this side always sends them too — correctness first; the native app
//   path is event-driven (the EVENT discipline), not spinning.
//
// Thread model matches the fd transport: one reader thread calls
// read_exact(); any thread calls write_all() under the caller's write lock.
#ifndef TPURPC_RING_TRANSPORT_H
#define TPURPC_RING_TRANSPORT_H

#include <fcntl.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>

#include "framing_common.h"

// C ring ops (ring.cc)
extern "C" {
uint64_t tpr_ring_read_into(uint8_t *ring, uint64_t cap, uint64_t *head,
                            uint64_t *msg_len, uint64_t *msg_read,
                            uint8_t *dst, uint64_t dst_len,
                            uint64_t *consumed, uint64_t *seq);
uint64_t tpr_ring_writev(uint8_t *ring, uint64_t cap, uint64_t *tail,
                         uint64_t remote_head, const uint8_t *const *segs,
                         const uint64_t *lens, uint32_t nsegs, uint64_t *seq);
uint64_t tpr_ring_max_payload(uint64_t cap);
uint64_t tpr_ring_reserve(uint8_t *ring, uint64_t cap, uint64_t tail,
                          uint64_t remote_head, uint64_t payload_len,
                          uint8_t **p1, uint64_t *l1,
                          uint8_t **p2, uint64_t *l2);
void tpr_ring_commit(uint8_t *ring, uint64_t cap, uint64_t *tail,
                     uint64_t payload_len, uint64_t *seq);
int tpr_ring_has_message(const uint8_t *ring, uint64_t cap, uint64_t head,
                         uint64_t msg_len, uint64_t seq);
void tpr_store_u64_seqcst(uint8_t *addr, uint64_t val);
uint64_t tpr_load_u64_fenced(const uint8_t *addr);
}

namespace tpr_ring {

constexpr size_t kStatusBytes = 128;
constexpr size_t kStatusHeadOff = 0;
constexpr size_t kStatusExitOff = 8;
constexpr uint64_t kReservedBytes = 24;  // header + footer + align gap
constexpr int kPublishDivisor = 4;       // RingReader.PUBLISH_DIVISOR

// ---------------------------------------------------------------------------
// POSIX shm region (the ShmDomain analog)
// ---------------------------------------------------------------------------

struct ShmRegion {
  std::string name;  // no leading slash (Python SharedMemory convention)
  uint8_t *base = nullptr;
  size_t len = 0;
  bool owner = false;

  bool create(size_t nbytes) {
    std::random_device rd;
    char buf[48];
    snprintf(buf, sizeof buf, "tpr_%08x%08x", rd(), rd());
    name = buf;
    std::string path = "/" + name;
    int fd = ::shm_open(path.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return false;
    if (::ftruncate(fd, (off_t)nbytes) != 0) {
      ::close(fd);
      ::shm_unlink(path.c_str());
      return false;
    }
    base = static_cast<uint8_t *>(::mmap(nullptr, nbytes,
                                         PROT_READ | PROT_WRITE, MAP_SHARED,
                                         fd, 0));
    ::close(fd);
    if (base == MAP_FAILED) {
      base = nullptr;
      ::shm_unlink(path.c_str());
      return false;
    }
    memset(base, 0, nbytes);
    len = nbytes;
    owner = true;
    return true;
  }

  bool open(const std::string &handle_name, size_t nbytes) {
    name = handle_name;
    std::string path = "/" + name;
    int fd = ::shm_open(path.c_str(), O_RDWR, 0600);
    if (fd < 0) return false;
    base = static_cast<uint8_t *>(::mmap(nullptr, nbytes,
                                         PROT_READ | PROT_WRITE, MAP_SHARED,
                                         fd, 0));
    ::close(fd);
    if (base == MAP_FAILED) {
      base = nullptr;
      return false;
    }
    len = nbytes;
    owner = false;
    return true;
  }

  void close() {
    if (base) ::munmap(base, len);
    base = nullptr;
    if (owner && !name.empty()) ::shm_unlink(("/" + name).c_str());
    name.clear();
  }
};

// ---------------------------------------------------------------------------
// Minimal JSON helpers for the Address blob (we control both producers;
// the fields are flat string/int/list-of-string)
// ---------------------------------------------------------------------------

inline bool json_find_string(const std::string &j, const char *key,
                             std::string *out) {
  std::string pat = std::string("\"") + key + "\":";
  size_t p = j.find(pat);
  if (p == std::string::npos) return false;
  p += pat.size();
  while (p < j.size() && (j[p] == ' ')) ++p;
  if (p >= j.size() || j[p] != '"') return false;
  size_t q = j.find('"', p + 1);
  if (q == std::string::npos) return false;
  *out = j.substr(p + 1, q - p - 1);
  return true;
}

inline bool json_find_u64(const std::string &j, const char *key,
                          uint64_t *out) {
  std::string pat = std::string("\"") + key + "\":";
  size_t p = j.find(pat);
  if (p == std::string::npos) return false;
  p += pat.size();
  while (p < j.size() && j[p] == ' ') ++p;
  char *end = nullptr;
  unsigned long long v = strtoull(j.c_str() + p, &end, 10);
  if (end == j.c_str() + p) return false;
  *out = v;
  return true;
}

// ---------------------------------------------------------------------------
// The transport
// ---------------------------------------------------------------------------

// The wakeup disciplines (SURVEY §2.3): RDMA_BP/BPEV busy-poll the ring
// words for a bounded slice before blocking in poll() — the reference's
// pollable_epoll spin (ev_epollex_rdma_bp_linux.cc:1020-1110), bounded by
// GRPC_RDMA_BUSY_POLLING_TIMEOUT_US (default 500us, README:17-25);
// RDMA_EVENT never spins. A single-hart host never spins either: the peer
// can't run while we burn the core, so spinning only delays its wakeup
// (the Python poller makes the same call — poller.py).
inline int spin_budget_us_from_env() {
  const char *p = getenv("TPURPC_PLATFORM_TYPE");
  if (!p) p = getenv("GRPC_PLATFORM_TYPE");
  if (!p || strcmp(p, "RDMA_EVENT") == 0) return 0;
  const char *t = getenv("TPURPC_BUSY_POLLING_TIMEOUT_US");
  if (!t) t = getenv("GRPC_RDMA_BUSY_POLLING_TIMEOUT_US");
  if (t) {  // explicit knob wins, single-hart or not (operator's call)
    long v = strtol(t, nullptr, 10);
    return v > 0 ? (int)v : 0;
  }
  if (std::thread::hardware_concurrency() <= 1) return 0;
  return 500;
}

struct RingTransport {
  int notify_fd = -1;          // the bootstrap socket, kept as event channel
  ShmRegion recv_ring, status;        // ours (peer writes into them)
  ShmRegion peer_ring, peer_status;   // peer's (we write into them)
  uint64_t ring_size = 0;       // our recv ring capacity
  uint64_t peer_ring_size = 0;  // peer's recv ring capacity (we send into it)

  // reader state (our ring)
  uint64_t head = 0, msg_len = 0, msg_read = 0, consumed = 0, rseq = 0;
  uint64_t published_head = 0;
  // writer state (peer ring)
  uint64_t tail = 0, wseq = 0, remote_head = 0;
  // wakeup discipline (BP/BPEV spin slice; 0 = EVENT / single-hart)
  int spin_us = spin_budget_us_from_env();

  std::atomic<bool> alive{false};
  std::atomic<bool> peer_exited{false};  // reader + writer threads both touch
  std::mutex notify_mu;  // serializes notify-token sends

  // -- bootstrap -----------------------------------------------------------

  // Client side: full TRB1 exchange on a fresh socket. Server side: pass
  // preread_magic=true when the listener already consumed the 4 magic
  // bytes while sniffing the protocol. timeout_ms bounds the handshake
  // (pair.py BOOTSTRAP_TIMEOUT_S: a peer that connects but never speaks
  // must produce an error, not a hang); <=0 keeps the 20s default.
  bool bootstrap(int fd, uint64_t my_ring_size, bool preread_magic,
                 std::string *err, int timeout_ms = 0) {
    notify_fd = fd;
    ring_size = my_ring_size;
    struct timeval tv;
    tv.tv_sec = timeout_ms > 0 ? timeout_ms / 1000 : 20;
    tv.tv_usec = timeout_ms > 0 ? (timeout_ms % 1000) * 1000 : 0;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    bool ok = bootstrap_inner(fd, preread_magic, err);
    tv.tv_sec = 0;
    tv.tv_usec = 0;  // back to blocking for the notify channel
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    return ok;
  }

  bool bootstrap_inner(int fd, bool preread_magic, std::string *err) {
    if (!recv_ring.create(ring_size) || !status.create(kStatusBytes)) {
      *err = "shm alloc failed";
      return false;
    }
    char tag[16];
    std::random_device rd;
    snprintf(tag, sizeof tag, "%08x", rd());
    char blob[512];
    int blen = snprintf(
        blob, sizeof blob,
        "{\"tag\": \"%s\", \"domain\": \"shm\", \"ring_size\": %llu, "
        "\"ring\": \"shm:%s\", \"status\": \"shm:%s\", \"caps\": []}",
        tag, (unsigned long long)ring_size, recv_ring.name.c_str(),
        status.name.c_str());
    // send: TRB1 + u32 len + blob
    char hdr[8] = {'T', 'R', 'B', '1'};
    uint32_t ln = (uint32_t)blen;
    memcpy(hdr + 4, &ln, 4);
    if (!tpr_wire::fd_write_all(fd, hdr, 8) ||
        !tpr_wire::fd_write_all(fd, blob, (size_t)blen)) {
      *err = "bootstrap send failed";
      return false;
    }
    // recv peer blob
    if (!preread_magic) {
      char magic[4];
      if (!tpr_wire::fd_read_exact(fd, magic, 4) ||
          memcmp(magic, "TRB1", 4) != 0) {
        *err = "bad bootstrap magic from peer (platform mismatch?)";
        return false;
      }
    }
    uint32_t plen = 0;
    if (!tpr_wire::fd_read_exact(fd, &plen, 4) || plen > (1u << 16)) {
      *err = "bootstrap length read failed";
      return false;
    }
    std::string pblob(plen, '\0');
    if (!tpr_wire::fd_read_exact(fd, pblob.data(), plen)) {
      *err = "bootstrap blob read failed";
      return false;
    }
    std::string domain, ring_h, status_h;
    uint64_t prs = 0;
    if (!json_find_string(pblob, "domain", &domain) ||
        !json_find_string(pblob, "ring", &ring_h) ||
        !json_find_string(pblob, "status", &status_h) ||
        !json_find_u64(pblob, "ring_size", &prs)) {
      *err = "malformed peer address blob";
      return false;
    }
    if (domain != "shm") {
      *err = "domain mismatch: peer offers '" + domain + "', this app is shm";
      return false;
    }
    if (ring_h.rfind("shm:", 0) != 0 || status_h.rfind("shm:", 0) != 0) {
      *err = "peer handles not shm";
      return false;
    }
    peer_ring_size = prs;
    if (!peer_ring.open(ring_h.substr(4), peer_ring_size) ||
        !peer_status.open(status_h.substr(4), kStatusBytes)) {
      *err = "mapping peer shm failed";
      return false;
    }
    alive.store(true);
    return true;
  }

  // -- byte-stream contract (same as the fd helpers) -----------------------

  bool write_all(const void *buf, size_t len) {
    const uint8_t *p = static_cast<const uint8_t *>(buf);
    while (len > 0 && alive.load()) {
      fold_credits();
      uint64_t writable = writable_now();
      if (writable == 0) {
        if (peer_gone()) return false;
        if (spin_for_credits()) continue;  // BP/BPEV: credits mid-spin
        if (!wait_event(100)) continue;  // slice + re-check (lost-notify safe)
        continue;
      }
      uint64_t n = len < writable ? len : writable;
      const uint8_t *segs[1] = {p};
      uint64_t lens[1] = {n};
      uint64_t got = tpr_ring_writev(peer_ring.base, peer_ring_size, &tail,
                                     remote_head, segs, lens, 1, &wseq);
      if (got == ~0ULL) continue;  // raced our own budget math
      p += got;
      len -= got;
      notify('d');
    }
    return len == 0;
  }

  // Whole-frame gather send: header + payload as ONE ring message with ONE
  // notify token — the per-RPC hot path (two write_all calls would cost two
  // framed messages and two notify syscalls). Falls back to sequential
  // write_all when the frame exceeds a single message's capacity.
  bool write_gather(const void *a, size_t alen, const void *b, size_t blen) {
    uint64_t total = alen + blen;
    uint64_t max_msg = peer_ring_size > kReservedBytes
                           ? peer_ring_size - kReservedBytes
                           : 0;
    if (total > max_msg)
      return write_all(a, alen) && (blen == 0 || write_all(b, blen));
    while (alive.load()) {
      fold_credits();
      if (writable_now() >= total) {
        const uint8_t *segs[2] = {static_cast<const uint8_t *>(a),
                                  static_cast<const uint8_t *>(b)};
        uint64_t lens[2] = {alen, blen};
        uint64_t got = tpr_ring_writev(peer_ring.base, peer_ring_size, &tail,
                                       remote_head, segs, lens,
                                       blen ? 2 : 1, &wseq);
        if (got != ~0ULL) {
          notify('d');
          return true;
        }
      }
      if (peer_gone()) return false;
      if (!spin_for_credits()) wait_event(100);
    }
    return false;
  }

  bool read_exact(void *buf, size_t len) {
    return read_exact_deadline(buf, len, nullptr) == 1;
  }

  // -- zero-copy send lease (SendZerocopy analog, pair.cc:793-941) ---------
  // Reserve ONE message's payload span in the peer ring (blocking for
  // credits like write_gather); the producer fills the returned (<=2,
  // wrap-split) segments in place — serialization targets the ring, no
  // staging copy — then commit_lease publishes (footer+header stamps) and
  // notifies. The caller must serialize reserve->commit against all other
  // sends on this transport (the channel write lock).
  bool reserve_lease(uint64_t payload_len, uint8_t **p1, uint64_t *l1,
                     uint8_t **p2, uint64_t *l2) {
    // can-NEVER-fit gate (same bound tpr_ring_reserve enforces, from the
    // same ring.cc home) — without it the credit loop below would wait
    // forever on a payload no amount of credits can grant
    if (payload_len == 0 ||
        payload_len > tpr_ring_max_payload(peer_ring_size))
      return false;
    while (alive.load()) {
      fold_credits();
      if (tpr_ring_reserve(peer_ring.base, peer_ring_size, tail, remote_head,
                           payload_len, p1, l1, p2, l2))
        return true;
      if (peer_gone()) return false;
      if (!spin_for_credits()) wait_event(100);
    }
    return false;
  }

  void commit_lease(uint64_t payload_len) {
    tpr_ring_commit(peer_ring.base, peer_ring_size, &tail, payload_len,
                    &wseq);
    notify('d');
  }

  // -- shared-poller (epoll) primitives ------------------------------------
  // The server's shared poller multiplexes many connections on one thread:
  // it epolls event_fd() (level-triggered), drains tokens, then pumps
  // read_some() until the ring is dry — no blocking read_exact on the
  // poller thread (the reference Poller's role, poller.cc:52-106).

  int event_fd() const { return notify_fd; }

  // Nonblocking drain of queued notify tokens. Returns -1 when the peer
  // closed the event channel (connection over), else the token count.
  // ALSO wakes any wait_event parkers: tokens are not addressed to a
  // particular waiter, so whoever drains them must publish "something
  // happened" to every blocked thread (see wait_event's epoch).
  int drain_tokens() {
    if (!epoll_tid_set.load(std::memory_order_acquire)) {
      // record the epoll loop's identity: ITS wait_event calls (a
      // callback handler blocking for response credits runs on this very
      // thread) must keep polling the fd — nobody else will — while
      // foreign threads park on ev_cv
      std::lock_guard<std::mutex> lk(ev_mu);
      epoll_tid = std::this_thread::get_id();
      epoll_tid_set.store(true, std::memory_order_release);
    }
    char tokens[256];
    int total = 0;
    while (true) {
      ssize_t n = ::recv(notify_fd, tokens, sizeof tokens, MSG_DONTWAIT);
      if (n == 0) {  // peer closed
        peer_exited = true;
        wake_waiters();
        return -1;
      }
      if (n < 0) break;  // EAGAIN: drained
      for (ssize_t i = 0; i < n; ++i)
        if (tokens[i] == 'x') peer_exited = true;
      total += static_cast<int>(n);
      if (n < static_cast<ssize_t>(sizeof tokens)) break;
    }
    if (total > 0) wake_waiters();
    return total;
  }

  void wake_waiters() {
    {
      std::lock_guard<std::mutex> lk(ev_mu);
      ++ev_epoch;
    }
    ev_cv.notify_all();
  }

  // Nonblocking ring read: up to `max` framing-stream bytes into buf.
  // Returns bytes read (0 = nothing available), or -1 when the stream is
  // over (peer gone with an empty ring, or corruption).
  ssize_t read_some(void *buf, size_t max) {
    uint64_t got = tpr_ring_read_into(recv_ring.base, ring_size, &head,
                                      &msg_len, &msg_read,
                                      static_cast<uint8_t *>(buf), max,
                                      &consumed, &rseq);
    if (got == ~0ULL) return -1;  // corruption
    if (got) {
      publish_credits_if_due();
      return static_cast<ssize_t>(got);
    }
    if (!alive.load() || ring_empty_and_peer_gone()) return -1;
    return 0;
  }

  // Deadline-aware read for the inline-pump discipline: 1 = filled,
  // -1 = dead, 0 = deadline passed with ZERO bytes consumed — the stream
  // is intact, so a frame-header read can be abandoned cleanly at a frame
  // boundary. Once any byte is consumed the deadline is ignored (the unit
  // must complete; peers write whole frames as one ring message on the
  // hot path, so the remainder is already in the ring).
  int read_exact_deadline(
      void *buf, size_t len,
      const std::chrono::steady_clock::time_point *deadline) {
    uint8_t *p = static_cast<uint8_t *>(buf);
    const size_t want = len;
    while (len > 0) {
      uint64_t got = tpr_ring_read_into(recv_ring.base, ring_size, &head,
                                        &msg_len, &msg_read, p, len,
                                        &consumed, &rseq);
      if (got == ~0ULL) return -1;  // corruption
      p += got;
      len -= got;
      publish_credits_if_due();
      if (len == 0) break;
      if (!alive.load()) return -1;
      if (ring_empty_and_peer_gone()) return -1;  // clean EOF
      int wait_ms = 100;
      if (deadline != nullptr && len == want) {
        auto now = std::chrono::steady_clock::now();
        if (now >= *deadline) return 0;
        auto rem = std::chrono::duration_cast<std::chrono::milliseconds>(
                       *deadline - now).count();
        if (rem < wait_ms) wait_ms = rem < 1 ? 1 : static_cast<int>(rem);
      }
      if (spin_for_message()) continue;  // BP/BPEV: data landed mid-spin
      wait_event(wait_ms);
    }
    return 1;
  }

  // Bounded busy-poll on the ring's header word (the BP/BPEV hot loop).
  // True = a message appeared; false = slice expired (caller blocks).
  bool spin_for_message() {
    if (spin_us <= 0) return false;
    auto end = std::chrono::steady_clock::now() +
               std::chrono::microseconds(spin_us);
    while (std::chrono::steady_clock::now() < end) {
      if (tpr_ring_has_message(recv_ring.base, ring_size, head, msg_len,
                               rseq))
        return true;
      if (!alive.load() || peer_exited.load()) return false;
#if defined(__x86_64__) || defined(__i386__)
      __builtin_ia32_pause();
#endif
    }
    return false;
  }

  void shutdown() {
    // graceful: tell the peer (exit word + token), then unblock our reader
    if (peer_status.base) {
      tpr_store_u64_seqcst(peer_status.base + kStatusExitOff, 1);
      notify('x');
    }
    alive.store(false);
    if (notify_fd >= 0) ::shutdown(notify_fd, SHUT_RDWR);
  }

  void close() {
    alive.store(false);
    recv_ring.close();
    status.close();
    peer_ring.close();
    peer_status.close();
  }

  // -- internals -----------------------------------------------------------

  void fold_credits() {
    uint64_t h = tpr_load_u64_fenced(status.base + kStatusHeadOff);
    if (h > remote_head && h <= tail) remote_head = h;
  }

  uint64_t writable_now() const {
    uint64_t used = tail - remote_head;
    return used + kReservedBytes >= peer_ring_size
               ? 0
               : peer_ring_size - used - kReservedBytes;
  }

  // Bounded busy-poll on the peer-published credit word (write twin of
  // spin_for_message; the reference's writer watches remote_head the same
  // way, pair.cc:294-301).
  bool spin_for_credits() {
    if (spin_us <= 0) return false;
    auto end = std::chrono::steady_clock::now() +
               std::chrono::microseconds(spin_us);
    while (std::chrono::steady_clock::now() < end) {
      fold_credits();
      if (writable_now() > 0) return true;
      if (!alive.load() || peer_gone()) return false;
#if defined(__x86_64__) || defined(__i386__)
      __builtin_ia32_pause();
#endif
    }
    return false;
  }

  bool peer_gone() {
    return tpr_load_u64_fenced(status.base + kStatusExitOff) != 0 ||
           peer_exited || !alive.load();
  }

  bool ring_empty_and_peer_gone() {
    if (!peer_gone()) return false;
    // peer exited, but drain whatever it wrote before leaving
    return !tpr_ring_has_message(recv_ring.base, ring_size, head, msg_len,
                                 rseq) &&
           msg_len == 0;
  }

  void publish_credits_if_due(bool force = false) {
    if (!peer_status.base) return;
    if (force || consumed >= ring_size / kPublishDivisor) {
      consumed = 0;
      if (head != published_head) {
        published_head = head;
        tpr_store_u64_seqcst(peer_status.base + kStatusHeadOff, head);
        notify('c');
      }
    }
  }

  void notify(char token) {
    std::lock_guard<std::mutex> lk(notify_mu);
    if (notify_fd < 0) return;
    ::send(notify_fd, &token, 1, MSG_NOSIGNAL | MSG_DONTWAIT);
    // EAGAIN => tokens already queued: the peer has wakeups pending
  }

  // Block up to timeout_ms for a notify token (or peer close). Returns true
  // if an event arrived (possibly drained by ANOTHER thread).
  //
  // Multiple threads legally block here at once — a reader waiting for
  // data and a writer waiting for credits share ONE notify fd, and the
  // tokens are not addressed. Two threads racing poll()+recv() on the fd
  // STEAL each other's wakeups: the reader can drain the writer's 'c'
  // credit token, re-check its (empty) ring, and sleep again, leaving the
  // writer to burn its full timeout while the peer has already returned
  // credits — measured as bulk sends moving exactly one ring per 100 ms
  // slice (~0.07 GB/s; 6-8x off). So: ONE thread polls the fd; everyone
  // else parks on a condition variable that the drainer (this poller, or
  // the server's epoll loop via drain_tokens) bumps for every drain.
  bool wait_event(int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    std::unique_lock<std::mutex> lk(ev_mu);
    uint64_t e = ev_epoch;
    for (;;) {
      // Is the fd owned by a shared epoll loop, and is this a FOREIGN
      // thread? Then a recv() here would steal 'd' tokens the
      // level-triggered epoll needs to pump requests (they'd sit unread in
      // the ring) — park for the owner's drain instead. The epoll thread
      // ITSELF (a callback handler blocking for response credits) keeps
      // polling: its pump_conn continuation drains the ring either way,
      // and nobody else would read the fd while it is blocked here.
      bool foreign = epoll_owned.load() &&
                     !(epoll_tid_set.load() &&
                       epoll_tid == std::this_thread::get_id());
      if (foreign) {
        ev_cv.wait_until(lk, deadline, [&] { return ev_epoch != e; });
        return ev_epoch != e;
      }
      if (ev_polling) {
        // Parked waiters also wake when the polling thread STANDS DOWN
        // (ev_polling -> false, e.g. its own timeout): one of them must
        // take over the fd poll, or queued tokens sit unread while every
        // parked waiter sleeps out its full timeout (ADVICE r5 — a
        // bounded re-run of the wake-latency bug this machinery fixed).
        ev_cv.wait_until(lk, deadline,
                         [&] { return ev_epoch != e || !ev_polling; });
        if (ev_epoch != e) return true;
        if (std::chrono::steady_clock::now() >= deadline) return false;
        continue;  // poller stood down with time left: take over the fd
      }
      ev_polling = true;
      lk.unlock();
      auto remain = std::chrono::duration_cast<std::chrono::milliseconds>(
                        deadline - std::chrono::steady_clock::now())
                        .count();
      if (remain < 0) remain = 0;
      struct pollfd pfd = {notify_fd, POLLIN, 0};
      int r = ::poll(&pfd, 1, static_cast<int>(remain));
      bool got = false;
      if (r > 0) {
        char tokens[64];
        ssize_t n = ::recv(notify_fd, tokens, sizeof tokens, MSG_DONTWAIT);
        if (n == 0) {  // peer closed the event channel: connection over
          peer_exited = true;
          got = true;
        } else if (n > 0) {
          for (ssize_t i = 0; i < n; ++i)
            if (tokens[i] == 'x') peer_exited = true;
          got = true;
        }
      }
      lk.lock();
      ev_polling = false;
      if (got) ++ev_epoch;
      bool advanced = ev_epoch != e;
      lk.unlock();
      ev_cv.notify_all();  // hand the fd off + deliver the drain
      return advanced;
    }
  }

  std::mutex ev_mu;
  std::condition_variable ev_cv;
  uint64_t ev_epoch = 0;   // bumped on every token drain (any drainer)
  bool ev_polling = false; // a thread owns the poll on notify_fd
  //: set when a shared epoll poller adopts this transport's fd
  //: (tpurpc_server.cc Poller::add): from then on only drain_tokens (the
  //: epoll loop) and the epoll thread's own wait_event calls touch the
  //: fd; foreign wait_event callers park on ev_cv
  std::atomic<bool> epoll_owned{false};
  std::thread::id epoll_tid{};           // ev_mu; valid once epoll_tid_set
  std::atomic<bool> epoll_tid_set{false};
};

}  // namespace tpr_ring

#endif  // TPURPC_RING_TRANSPORT_H
