// tpurpc C server implementation — native app servers over the framing.
//
// Wire format: tpurpc/rpc/frame.py via framing_common.h. Model: accept-loop
// thread + one reader thread per connection that DEMUXES frames to
// per-stream call objects (tpurpc Python channels multiplex concurrent
// calls over one connection, so per-stream routing is mandatory, not a
// nicety); each call's handler runs on its own thread. The reference's
// equivalent machinery is src/cpp/server/ + surface/server.cc's
// registered-method dispatch, collapsed to tpurpc scale.

#include "../include/tpurpc/server.h"

#include "ring_transport.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "framing_common.h"

using namespace tpr_wire;

namespace {
using Clock = std::chrono::steady_clock;
struct Conn;
}  // namespace

struct tpr_server_call {
  Conn *conn = nullptr;
  uint32_t stream_id = 0;
  std::string method;
  int64_t deadline_us = INT64_MAX;  // absolute, vs Clock epoch
  std::string details;

  // reader-thread-filled state, guarded by conn->mu
  std::deque<std::string> pending;  // complete messages
  std::string partial;              // MORE-fragment accumulator
  bool half_closed = false;         // client END_STREAM seen
  bool cancelled = false;           // RST / connection death

  // callback-API calls: handled inline on the reader thread (no thread,
  // no pending queue — each complete message goes straight to the cb)
  int (*inline_cb)(tpr_server_call *, const uint8_t *, size_t, void *) =
      nullptr;
  void *inline_ud = nullptr;
};

namespace {

struct Conn {
  int fd = -1;
  // non-null when this connection bootstrapped the shm ring data plane
  // (client opened with the TRB1 magic): frames ride the ring, the fd
  // stays inside the transport as the notify channel
  tpr_ring::RingTransport *ring = nullptr;
  std::mutex write_mu;             // serializes whole frames
  std::mutex mu;                   // guards streams + call state
  std::condition_variable cv;      // signaled on any delivery
  std::map<uint32_t, tpr_server_call *> streams;
  std::atomic<bool> alive{true};
  std::atomic<bool> fd_closed{false};
  std::thread thread;
  std::atomic<int> handler_threads{0};

  ~Conn() {
    if (ring) {
      ring->close();
      delete ring;
    }
  }

  bool write_all(const void *buf, size_t len) {
    return ring ? ring->write_all(buf, len) : fd_write_all(fd, buf, len);
  }

  bool read_exact(void *buf, size_t len) {
    return ring ? ring->read_exact(buf, len) : fd_read_exact(fd, buf, len);
  }

  bool send_frame(uint8_t type, uint8_t flags, uint32_t sid,
                  const void *payload, size_t len) {
    std::lock_guard<std::mutex> lk(write_mu);
    if (fd_closed.load()) return false;
    if (ring)  // one gathered ring message + one notify per frame
      return ring_send_frame_locked(*ring, type, flags, sid, payload, len);
    return t_send_frame_locked(*this, type, flags, sid, payload, len);
  }

  void send_trailers(uint32_t sid, int code, const std::string &details) {
    std::vector<std::pair<std::string, std::string>> md;
    md.emplace_back(":status", std::to_string(code));
    if (!details.empty()) md.emplace_back(":message", details);
    std::string payload = encode_metadata(md);
    send_frame(kTrailers, kFlagEndStream, sid, payload.data(), payload.size());
  }

  void close_fd() {
    // write_mu excludes a concurrent send_frame mid-write on the dying fd;
    // the flag (checked under write_mu) prevents double close / fd reuse.
    std::lock_guard<std::mutex> lk(write_mu);
    if (!fd_closed.exchange(true)) {
      if (ring) ring->shutdown();  // exit word + notify before fd close
      ::close(fd);
    }
  }

  void shutdown_fd() {
    // Same discipline as close_fd: the check and the shutdown must be one
    // critical section, or a racing close_fd can recycle the fd number
    // between them and this shutdown() hits an unrelated descriptor.
    std::lock_guard<std::mutex> lk(write_mu);
    if (!fd_closed.load()) {
      if (ring) ring->shutdown();
      ::shutdown(fd, SHUT_RDWR);
    }
  }
};

}  // namespace

struct tpr_server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::map<std::string, std::pair<tpr_handler_fn, void *>> handlers;
  std::map<std::string, std::pair<tpr_msg_cb, void *>> cb_handlers;
  std::mutex conns_mu;
  std::vector<Conn *> conns;

  void run_handler(Conn *c, tpr_server_call *call) {
    auto it = handlers.find(call->method);
    int code;
    if (it == handlers.end()) {
      code = 12;  // UNIMPLEMENTED
      call->details = "unknown method " + call->method;
    } else {
      code = it->second.first(call, it->second.second);
    }
    bool was_cancelled;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      was_cancelled = call->cancelled;
      c->streams.erase(call->stream_id);
    }
    if (!was_cancelled) c->send_trailers(call->stream_id, code, call->details);
    delete call;
    c->handler_threads.fetch_sub(1);
  }

  // Protocol sniff + preface, mirroring the Python listener (peek_protocol,
  // endpoint.py): ring clients open with the 4-byte TRB1 bootstrap magic;
  // plain framing clients send the 8-byte TPURPC preface. False = dead conn.
  bool accept_preface(Conn *c) {
    char magic[8];
    if (!fd_read_exact(c->fd, magic, 4)) return false;
    if (memcmp(magic, "TRB1", 4) == 0) {
      auto *rt = new tpr_ring::RingTransport();
      std::string err;
      if (!rt->bootstrap(c->fd, tpr_wire::ring_size_from_env(),
                         /*preread_magic=*/true, &err)) {
        fprintf(stderr, "tpurpc server: ring bootstrap failed: %s\n",
                err.c_str());
        rt->close();
        delete rt;
        return false;
      }
      c->ring = rt;
      // the framing preface now rides the ring byte stream
      return c->read_exact(magic, 8) && memcmp(magic, kMagic, 8) == 0;
    }
    return fd_read_exact(c->fd, magic + 4, 4) &&
           memcmp(magic, kMagic, 8) == 0;
  }

  void serve_conn(Conn *c) {
    bool serving = accept_preface(c);
    // a failed preface still falls through to the shared teardown below:
    // early returns here used to leak the Conn (alive stayed true, so
    // reap_dead_conns never freed it) and its fd
    uint8_t type, flags;
    uint32_t sid;
    std::vector<uint8_t> payload;
    while (serving && running.load() && c->alive.load()) {
      if (!t_read_frame(*c, &type, &flags, &sid, &payload)) break;
      if (type == kPing) {
        c->send_frame(kPong, 0, 0, payload.data(), payload.size());
        continue;
      }
      if (type == kHeaders) {
        std::vector<std::pair<std::string, std::string>> md;
        if (!decode_metadata(payload.data(), payload.size(), &md)) break;
        auto *call = new tpr_server_call();
        call->conn = c;
        call->stream_id = sid;
        for (auto &kv : md) {
          if (kv.first == ":path") call->method = kv.second;
          else if (kv.first == ":timeout-us")
            call->deadline_us =
                std::chrono::duration_cast<std::chrono::microseconds>(
                    Clock::now().time_since_epoch()).count() +
                atoll(kv.second.c_str());
        }
        bool duplicate;
        {
          std::lock_guard<std::mutex> lk(c->mu);
          duplicate = c->streams.count(sid) != 0;
          if (!duplicate) c->streams[sid] = call;
        }
        if (duplicate) {
          // duplicate HEADERS on an active sid: protocol violation —
          // overwriting would orphan one call's frame routing forever
          c->send_trailers(sid, 13, "duplicate stream id");  // INTERNAL
          delete call;
          continue;
        }
        auto cb_it = cb_handlers.find(call->method);
        if (cb_it != cb_handlers.end()) {
          // callback API: no thread — messages dispatch inline below
          call->inline_cb = cb_it->second.first;
          call->inline_ud = cb_it->second.second;
          if (flags & kFlagEndStream) {  // empty call: trailers now
            {
              std::lock_guard<std::mutex> lk2(c->mu);
              c->streams.erase(sid);
            }
            c->send_trailers(sid, 0, call->details);
            delete call;
          }
          continue;
        }
        c->handler_threads.fetch_add(1);
        std::thread([this, c, call] { run_handler(c, call); }).detach();
        continue;
      }
      // frame for an existing stream
      if (type == kMessage && (flags & kFlagCompressed)) {
        // loud protocol rejection: this loop has no decompressor, and
        // delivering gzip bytes as the message would corrupt the app
        std::unique_lock<std::mutex> lk(c->mu);
        auto it = c->streams.find(sid);
        if (it != c->streams.end()) {
          tpr_server_call *call = it->second;
          // Erase the stream NOW in both branches: a fragmented compressed
          // message delivers kFlagCompressed on every fragment, and later
          // fragments must fall into the finished/unknown drop instead of
          // re-sending these trailers. The details text must keep
          // "compressed messages unsupported" as a substring — the Python
          // channel's compression negotiation keys on it
          // (tpurpc/rpc/frame.py COMPRESSED_UNSUPPORTED_SENTINEL).
          c->streams.erase(it);
          if (call->inline_cb) {
            lk.unlock();
            c->send_trailers(sid, 12 /*UNIMPLEMENTED*/,
                             "compressed messages unsupported here");
            delete call;
          } else {
            call->cancelled = true;  // handler exits; run_handler frees
            lk.unlock();
            c->send_trailers(sid, 12 /*UNIMPLEMENTED*/,
                             "compressed messages unsupported here");
            c->cv.notify_all();
          }
        }
        continue;
      }
      std::unique_lock<std::mutex> lk(c->mu);
      auto it = c->streams.find(sid);
      if (it == c->streams.end()) continue;  // finished/unknown: drop
      tpr_server_call *call = it->second;
      if (call->inline_cb) {
        // reactor path: complete messages run the cb ON THIS THREAD;
        // teardown is immediate at RST/half-close/nonzero-return. Only the
        // reader touches inline calls, so the lock is released first.
        lk.unlock();
        bool finished = false;
        bool rst = false;
        int code = 0;
        if (type == kRst) {
          finished = rst = true;  // cancelled: client left, no trailers
        } else if (type == kMessage) {
          const bool has_payload = !(flags & kFlagNoMessage);
          const bool complete = has_payload && !(flags & kFlagMore);
          if (complete && call->partial.empty()) {
            // common case: whole message in one frame — feed the cb the
            // frame buffer directly, no accumulator alloc/copy
            code = call->inline_cb(call, payload.data(), payload.size(),
                                   call->inline_ud);
          } else {
            if (has_payload)
              call->partial.append(reinterpret_cast<char *>(payload.data()),
                                   payload.size());
            if (complete) {
              std::string msg = std::move(call->partial);
              call->partial.clear();
              code = call->inline_cb(
                  call, reinterpret_cast<const uint8_t *>(msg.data()),
                  msg.size(), call->inline_ud);
            }
          }
          // negative returns are app errors, not a protocol escape hatch:
          // map them to INTERNAL so the client always gets trailers
          if (code < 0) code = 13;
          if (code != 0 || (flags & kFlagEndStream)) finished = true;
        }
        if (finished) {
          {
            std::lock_guard<std::mutex> lk2(c->mu);
            c->streams.erase(sid);
          }
          if (!rst) c->send_trailers(sid, code, call->details);
          delete call;
        }
        continue;
      }
      if (type == kRst) {
        call->cancelled = true;
      } else if (type == kMessage) {
        if (!(flags & kFlagNoMessage))
          call->partial.append(reinterpret_cast<char *>(payload.data()),
                               payload.size());
        if (!(flags & kFlagMore) && !(flags & kFlagNoMessage)) {
          call->pending.push_back(std::move(call->partial));
          call->partial.clear();
        }
        if (flags & kFlagEndStream) call->half_closed = true;
      }
      lk.unlock();
      c->cv.notify_all();
    }
    // connection done: fail outstanding calls, wake their handlers
    {
      std::lock_guard<std::mutex> lk(c->mu);
      for (auto &kv : c->streams) kv.second->cancelled = true;
    }
    c->cv.notify_all();
    // wait for handlers to drain (they hold call pointers)
    while (c->handler_threads.load() > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    {
      // inline (callback-API) calls have no handler thread to free them:
      // whatever is left in the map now is reader-owned — reap it here
      std::lock_guard<std::mutex> lk(c->mu);
      for (auto &kv : c->streams) delete kv.second;
      c->streams.clear();
    }
    c->close_fd();
    c->alive.store(false);
  }

  void reap_dead_conns() {
    std::lock_guard<std::mutex> lk(conns_mu);
    for (auto it = conns.begin(); it != conns.end();) {
      Conn *c = *it;
      if (!c->alive.load()) {
        if (c->thread.joinable()) c->thread.join();
        delete c;
        it = conns.erase(it);
      } else {
        ++it;
      }
    }
  }

  void accept_loop() {
    while (running.load()) {
      struct sockaddr_in peer {};
      socklen_t plen = sizeof peer;
      int fd = ::accept(listen_fd, reinterpret_cast<sockaddr *>(&peer), &plen);
      if (fd < 0) {
        if (errno == EINTR) continue;
        return;  // listener closed
      }
      reap_dead_conns();  // bound growth: finished conns freed on each accept
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      auto *c = new Conn();
      c->fd = fd;
      {
        std::lock_guard<std::mutex> lk(conns_mu);
        conns.push_back(c);
      }
      c->thread = std::thread([this, c] { serve_conn(c); });
    }
  }
};

// ---------------------------------------------------------------------------

extern "C" {

tpr_server *tpr_server_create(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr {};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof addr) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  auto *s = new tpr_server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  return s;
}

int tpr_server_port(tpr_server *s) { return s->port; }

void tpr_server_register(tpr_server *s, const char *method, tpr_handler_fn fn,
                         void *ud) {
  s->handlers[method] = {fn, ud};
}

void tpr_server_register_callback(tpr_server *s, const char *method,
                                  tpr_msg_cb on_msg, void *ud) {
  s->cb_handlers[method] = {on_msg, ud};
}

int tpr_server_start(tpr_server *s) {
  s->running.store(true);
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return 0;
}

void tpr_server_destroy(tpr_server *s) {
  s->running.store(false);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    for (Conn *c : s->conns) {
      c->alive.store(false);
      c->shutdown_fd();
      if (c->thread.joinable()) c->thread.join();
      delete c;
    }
    s->conns.clear();
  }
  delete s;
}

int tpr_srv_recv(tpr_server_call *c, uint8_t **data, size_t *len) {
  Conn *conn = c->conn;
  std::unique_lock<std::mutex> lk(conn->mu);
  while (true) {
    if (!c->pending.empty()) {
      std::string &m = c->pending.front();
      *len = m.size();
      *data = static_cast<uint8_t *>(malloc(m.size() ? m.size() : 1));
      memcpy(*data, m.data(), m.size());
      c->pending.pop_front();
      return 1;
    }
    if (c->cancelled) return -1;
    if (c->half_closed) return 0;
    conn->cv.wait(lk);
  }
}

int tpr_srv_send(tpr_server_call *c, const uint8_t *data, size_t len) {
  size_t off = 0;
  do {
    size_t n = len - off;
    bool last = n <= kMaxFramePayload;
    if (!last) n = kMaxFramePayload;
    uint8_t flags = last ? 0 : kFlagMore;
    if (!c->conn->send_frame(kMessage, flags, c->stream_id, data + off, n))
      return -1;
    off += n;
  } while (off < len);
  return 0;
}

const char *tpr_srv_method(tpr_server_call *c) { return c->method.c_str(); }

int64_t tpr_srv_deadline_us(tpr_server_call *c) {
  if (c->deadline_us == INT64_MAX) return INT64_MAX;
  int64_t now = std::chrono::duration_cast<std::chrono::microseconds>(
                    Clock::now().time_since_epoch()).count();
  int64_t left = c->deadline_us - now;
  return left > 0 ? left : 0;
}

void tpr_srv_set_details(tpr_server_call *c, const char *details) {
  c->details = details ? details : "";
}

void tpr_srv_buf_free(uint8_t *data) { free(data); }

}  // extern "C"
