// tpurpc C server implementation — native app servers over the framing.
//
// Wire format: tpurpc/rpc/frame.py via framing_common.h.
//
// Threading model (round 4, replacing thread-per-connection): connections
// are multiplexed over a FIXED set of poller threads — the role of the
// reference's Poller (src/core/lib/ibverbs/poller.cc:52-106, which
// round-robins up to 4096 pairs over N background threads). Each poller
// owns an epoll set of its connections' event fds (the TCP data fd, or the
// ring's notify fd) and parses frames INCREMENTALLY per connection, so one
// thread serves any number of connections and a 128-connection fan-in
// costs 1 poller + handler threads, not 128 readers. The accept loop only
// accepts; a short-lived thread per NEW connection runs the (blocking,
// bounded) protocol sniff + ring bootstrap, then hands the connection to a
// poller and exits.
//
// Call dispatch is unchanged: frames demux to per-stream call objects
// (tpurpc Python channels multiplex concurrent calls over one connection);
// callback-API handlers run inline on the poller thread; handler-API calls
// run on a thread each (they block in tpr_srv_recv). The reference's
// equivalent machinery is src/cpp/server/ + surface/server.cc's
// registered-method dispatch, collapsed to tpurpc scale.

#include "../include/tpurpc/server.h"

#include "ring_transport.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <sched.h>
#include <sys/epoll.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "framing_common.h"
#include "tpr_obs.h"
#include "tpr_rdv.h"

using namespace tpr_wire;

namespace {
using Clock = std::chrono::steady_clock;
struct Conn;
}  // namespace

// Message accumulator backed by malloc from the start, so tpr_srv_recv can
// hand ownership straight to the handler (the tpr_srv_buf_free contract is
// free()) with ZERO copy — the old std::string deque paid a malloc+memcpy
// per delivered message, one full extra pass on the bulk path.
struct OwnedBuf {
  uint8_t *p = nullptr;
  size_t len = 0;
  size_t cap = 0;
  // true when p points into a rendezvous landing region (tpr_rdv): NOT a
  // malloc chunk — disposal must settle() (ring the doorbell / recycle),
  // never free(). tpr_srv_buf_free consults the same registry, so the
  // handler-facing contract is unchanged either way.
  bool ext = false;

  // move-only: a raw-owning struct that the compiler lets you copy is a
  // double free waiting for a maintainer (the container moves below are
  // the only ownership transfers)
  OwnedBuf() = default;
  OwnedBuf(const OwnedBuf &) = delete;
  OwnedBuf &operator=(const OwnedBuf &) = delete;
  OwnedBuf(OwnedBuf &&o) noexcept
      : p(o.p), len(o.len), cap(o.cap), ext(o.ext) {
    o.p = nullptr;
    o.len = o.cap = 0;
    o.ext = false;
  }
  OwnedBuf &operator=(OwnedBuf &&o) noexcept {
    if (this != &o) {
      dispose();
      p = o.p;
      len = o.len;
      cap = o.cap;
      ext = o.ext;
      o.p = nullptr;
      o.len = o.cap = 0;
      o.ext = false;
    }
    return *this;
  }
  ~OwnedBuf() { dispose(); }

  void dispose() {
    if (p == nullptr) return;
    if (!ext || !tpr_rdv::settle(p)) free(p);
    p = nullptr;
  }

  // take ownership of an existing buffer: a malloc chunk (rdv=false) or a
  // delivered landing-region pointer (rdv=true)
  void adopt(uint8_t *buf, size_t n, bool rdv) {
    dispose();
    p = buf;
    len = cap = n;
    ext = rdv;
  }

  void append(const uint8_t *src, size_t n) {
    if (n == 0) return;  // empty message: memcpy(NULL,..,0) is still UB
    if (len + n > cap) {
      // 64-byte-aligned storage (freeable with free(), so the
      // tpr_srv_buf_free contract is unchanged): the tensor codec lays
      // leaves out on 64-byte offsets, so an aligned message base is what
      // lets the Python binding's dlpack import alias the receive buffer
      // into a jax.Array with zero copy — glibc's mmap'd malloc chunks sit
      // at 16 mod 64 and force a 4 MiB landing copy per message.
      // aligned_alloc can't mremap-grow like realloc, so fragmented
      // messages (a MORE first fragment) reserve 8x the fragment upfront:
      // one allocation covers the whole message for anything ≤ 8 frames,
      // and the doubling copy is the rare tail, not the steady state.
      size_t want = cap ? cap * 2 : (n > 4096 ? n * 8 : 4096);
      while (want < len + n) want *= 2;
      uint8_t *np = static_cast<uint8_t *>(aligned_alloc(64, want));
      if (np == nullptr) abort();  // OOM: same fate as the old path's
      if (len) memcpy(np, p, len);  // uncaught bad_alloc, without the UB
      free(p);
      p = np;
      cap = want;
    }
    memcpy(p + len, src, n);
    len += n;
  }

  // hand the malloc'd buffer to the caller (who frees with free())
  uint8_t *release(size_t *out_len) {
    uint8_t *out = p ? p : static_cast<uint8_t *>(malloc(1));
    *out_len = len;
    p = nullptr;
    len = cap = 0;
    return out;
  }

  void reset() { *this = OwnedBuf(); }
};

struct tpr_server_call {
  Conn *conn = nullptr;
  uint32_t stream_id = 0;
  std::string method;
  int64_t deadline_us = INT64_MAX;  // absolute, vs Clock epoch
  std::string details;
  //: every request header except :path/:timeout-us (exposed to handlers —
  //: the invocation_metadata a language-level server needs)
  std::vector<std::pair<std::string, std::string>> md;
  //: queued initial metadata; shipped as a HEADERS frame before the first
  //: response message
  std::vector<std::pair<std::string, std::string>> initial_md;
  bool initial_md_sent = false;
  //: custom trailing metadata appended to the final trailers
  std::vector<std::pair<std::string, std::string>> trailing_md;

  // reader/poller-filled state, guarded by conn->mu
  std::deque<OwnedBuf> pending;  // complete messages (malloc-backed)
  OwnedBuf partial;              // MORE-fragment accumulator
  bool half_closed = false;      // client END_STREAM seen
  bool cancelled = false;        // RST / connection death

  // callback-API calls: handled inline on the poller thread (no thread,
  // no pending queue — each complete message goes straight to the cb)
  int (*inline_cb)(tpr_server_call *, const uint8_t *, size_t, void *) =
      nullptr;
  void *inline_ud = nullptr;

};

namespace {

struct Poller;

struct Conn {
  int fd = -1;
  // non-null when this connection bootstrapped the shm ring data plane
  // (client opened with the TRB1 magic): frames ride the ring, the fd
  // stays inside the transport as the notify channel
  tpr_ring::RingTransport *ring = nullptr;
  std::mutex write_mu;             // serializes whole frames
  std::mutex mu;                   // guards streams + call state
  std::condition_variable cv;      // signaled on any delivery
  std::map<uint32_t, tpr_server_call *> streams;
  std::atomic<bool> alive{true};
  std::atomic<bool> fd_closed{false};
  std::atomic<int> handler_threads{0};
  // rendezvous + ctrl-ring side of this connection (tpr_rdv.h); created at
  // bootstrap, armed only if the peer's hello negotiates
  tpr_rdv::Link *link = nullptr;
  // tpurpc-xray conn tag, interned once when bootstrap succeeds (the
  // tpr-obs static-tag discipline); 0 = plane off or never bootstrapped
  uint16_t otag_conn = 0;
  // delivery-shard items in flight for this conn: reap must wait for zero
  // (an item holds a raw Conn*)
  std::atomic<int> delivery_refs{0};
  //: teardown ran (streams failed, fd closed)
  std::atomic<bool> finished{false};
  //: safe to free: set only after the conn's poller can no longer hold a
  //: stale epoll event for it (end of the batch that finished it), or by
  //: non-poller finishers — reap requires it (frees must not race a
  //: same-batch duplicate event's `finished` load)
  std::atomic<bool> reapable{false};
  Poller *poller = nullptr;  // the poller serving this conn (post-bootstrap)

  // -- incremental frame parse (poller-thread-owned) -----------------------
  uint8_t hdr[10];
  size_t got = 0;            // bytes of the CURRENT unit (header or payload)
  bool in_payload = false;
  uint8_t f_type = 0, f_flags = 0;
  uint32_t f_sid = 0;
  size_t f_len = 0;
  std::vector<uint8_t> payload;

  ~Conn() {
    delete link;  // ~Link closes: discards leases, unmaps rings/windows
    if (ring) {
      ring->close();
      delete ring;
    }
  }

  int event_fd() const { return ring ? ring->event_fd() : fd; }

  bool write_all(const void *buf, size_t len) {
    return ring ? ring->write_all(buf, len) : fd_write_all(fd, buf, len);
  }

  bool read_exact(void *buf, size_t len) {
    return ring ? ring->read_exact(buf, len) : fd_read_exact(fd, buf, len);
  }

  // Nonblocking byte-stream read for the poller: >0 bytes, 0 would-block,
  // -1 dead. TCP uses MSG_DONTWAIT (the fd itself stays blocking so
  // handler-thread WRITES keep their simple semantics).
  ssize_t read_some(void *buf, size_t max) {
    if (ring) return ring->read_some(buf, max);
    ssize_t n = ::recv(fd, buf, max, MSG_DONTWAIT);
    if (n > 0) return n;
    if (n == 0) return -1;  // EOF
    return (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) ? 0
                                                                       : -1;
  }

  bool send_frame(uint8_t type, uint8_t flags, uint32_t sid,
                  const void *payload_, size_t len) {
    std::lock_guard<std::mutex> lk(write_mu);
    if (fd_closed.load()) return false;
    bool ok = ring  // one gathered ring message + one notify per frame
                  ? ring_send_frame_locked(*ring, type, flags, sid,
                                           payload_, len)
                  : t_send_frame_locked(*this, type, flags, sid, payload_,
                                        len);
    // EVERY frame actually written counts (ctrl-ring records stamp this
    // value as their ordering gate; an overcount would strand records)
    if (ok && link) link->frames_sent.fetch_add(1, std::memory_order_release);
    return ok;
  }

  void send_trailers(uint32_t sid, int code, const std::string &details,
                     const std::vector<std::pair<std::string, std::string>>
                         *extra_md = nullptr) {
    std::vector<std::pair<std::string, std::string>> md;
    md.emplace_back(":status", std::to_string(code));
    if (!details.empty()) md.emplace_back(":message", details);
    if (extra_md)
      for (const auto &kv : *extra_md) md.push_back(kv);
    std::string payload_ = encode_metadata(md);
    send_frame(kTrailers, kFlagEndStream, sid, payload_.data(),
               payload_.size());
  }

  void finish_call_trailers(tpr_server_call *call, int code) {
    send_trailers(call->stream_id, code, call->details,
                  call->trailing_md.empty() ? nullptr : &call->trailing_md);
  }

  void close_fd() {
    // write_mu excludes a concurrent send_frame mid-write on the dying fd;
    // the flag (checked under write_mu) prevents double close / fd reuse.
    std::lock_guard<std::mutex> lk(write_mu);
    if (!fd_closed.exchange(true)) {
      if (ring) ring->shutdown();  // exit word + notify before fd close
      ::close(fd);
    }
  }

  void shutdown_fd() {
    // Same discipline as close_fd: the check and the shutdown must be one
    // critical section, or a racing close_fd can recycle the fd number
    // between them and this shutdown() hits an unrelated descriptor.
    std::lock_guard<std::mutex> lk(write_mu);
    if (!fd_closed.load()) {
      if (ring) ring->shutdown();
      ::shutdown(fd, SHUT_RDWR);
    }
  }
};

// One epoll loop serving N connections (the reference Poller role). Conns
// are added via a locked pending list + wake pipe (epoll_ctl from another
// thread is safe, but the add must also trigger an initial pump — ring
// data that landed during bootstrap sends no further notify token).
struct Poller {
  int epfd = -1;
  int wake_r = -1, wake_w = -1;
  std::thread th;
  std::mutex add_mu;
  std::vector<Conn *> pending_add;
  std::atomic<bool> running{true};
  tpr_server *srv = nullptr;

  bool init() {
    epfd = ::epoll_create1(0);
    if (epfd < 0) return false;
    int p[2];
    if (::pipe(p) != 0) return false;
    wake_r = p[0];
    wake_w = p[1];
    ::fcntl(wake_r, F_SETFL, O_NONBLOCK);  // drain loop must never block
    struct epoll_event ev = {};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;  // null = wake pipe
    ::epoll_ctl(epfd, EPOLL_CTL_ADD, wake_r, &ev);
    return true;
  }

  void add(Conn *c) {
    // From adoption on, this epoll loop is the fd's only reader: blocked
    // writers (handler responses waiting for credits) must park on the
    // transport cv, not steal request tokens out of epoll's mouth
    // (ring_transport.h wait_event epoll_owned).
    if (c->ring) c->ring->epoll_owned.store(true);
    {
      std::lock_guard<std::mutex> lk(add_mu);
      pending_add.push_back(c);
    }
    char b = 'a';
    (void)!::write(wake_w, &b, 1);
  }

  void wake() {
    char b = 'w';
    (void)!::write(wake_w, &b, 1);
  }

  void stop_and_join() {
    running.store(false);
    wake();
    if (th.joinable()) th.join();
    if (epfd >= 0) ::close(epfd);
    if (wake_r >= 0) ::close(wake_r);
    if (wake_w >= 0) ::close(wake_w);
  }

  void loop();  // defined after tpr_server (needs dispatch)
};

}  // namespace

struct tpr_server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::map<std::string, std::pair<tpr_handler_fn, void *>> handlers;
  std::map<std::string, std::pair<tpr_msg_cb, void *>> cb_handlers;
  tpr_handler_fn default_handler = nullptr;  // unknown-method fallback
  void *default_ud = nullptr;
  std::mutex conns_mu;
  std::vector<Conn *> conns;
  std::vector<Poller *> pollers;
  std::atomic<size_t> next_poller{0};
  std::atomic<int> bootstrap_threads{0};

  // -- delivery shard (tentpole 3): decode/materialization off the poller --
  // On negotiated connections (and when enabled — TPURPC_NATIVE_DELIVERY,
  // auto = on with >= 2 cores) completed messages, half-closes and RSTs go
  // through ONE FIFO drained by a dedicated thread, so the poller does
  // nothing but land bytes and publish. Rendezvous deliveries ride the same
  // queue, which is what keeps framed and rdv messages of one stream in
  // order. Items pin their Conn via delivery_refs (reap waits for zero).
  struct DeliveryItem {
    Conn *c;
    uint32_t sid;
    uint8_t flags;
    uint8_t *data;  // malloc (rdv=false) or landing region (rdv=true)
    size_t len;
    bool rdv;
    bool rst;
  };
  std::thread delivery_th;
  std::mutex dq_mu;
  std::condition_variable dq_cv;
  std::deque<DeliveryItem> dq;
  std::atomic<bool> delivery_on{false};
  bool dq_stop = false;
  // tpurpc-xray delivery-shard backlog tracking (both under dq_mu): the
  // stall edge fires on a high-water crossing, clears below low water, so
  // a busy-but-draining queue emits nothing
  uint16_t otag_dlv = 0;
  bool dlv_stalled = false;
  static constexpr size_t kDlvHighWater = 64;
  static constexpr size_t kDlvLowWater = 8;

  static bool delivery_from_env() {
    const char *v = getenv("TPURPC_NATIVE_DELIVERY");
    if (v) {
      if (strcmp(v, "0") == 0 || strcasecmp(v, "off") == 0 ||
          strcasecmp(v, "false") == 0)
        return false;
      if (strcasecmp(v, "auto") != 0) return true;
    }
    // the measured reason the memcpy gate was inapplicable on 1 core: a
    // shard there just adds a handoff to the only hart
    return std::thread::hardware_concurrency() >= 2;
  }

  static void dispose_payload(uint8_t *data, bool rdv) {
    if (data == nullptr) return;
    if (!rdv || !tpr_rdv::settle(data)) free(data);
  }

  void enqueue_delivery(Conn *c, uint32_t sid, uint8_t flags, uint8_t *data,
                        size_t len, bool rdv, bool rst = false) {
    c->delivery_refs.fetch_add(1);
    {
      std::lock_guard<std::mutex> lk(dq_mu);
      dq.push_back(DeliveryItem{c, sid, flags, data, len, rdv, rst});
      size_t depth = dq.size();
      tpr_obs::metric_add(tpr_obs::kMetDlvEnqueued);
      tpr_obs::metric_store(tpr_obs::kMetDlvDepth, depth);
      if (depth >= kDlvHighWater && !dlv_stalled && otag_dlv) {
        dlv_stalled = true;
        tpr_obs::metric_add(tpr_obs::kMetDlvStalls);
        TPR_OBS(tpr_obs::kEvDlvStallBegin, otag_dlv, depth, 0);
      }
    }
    dq_cv.notify_one();
  }

  // The single delivery entry: runs on the shard when enabled, inline on
  // the poller otherwise. data==nullptr is a pure marker (half-close/RST).
  void deliver_msg(Conn *c, uint32_t sid, uint8_t flags, uint8_t *data,
                   size_t len, bool rdv, bool rst) {
    if (c->finished.load()) {  // conn tore down with this item in flight
      dispose_payload(data, rdv);
      return;
    }
    std::unique_lock<std::mutex> lk(c->mu);
    auto it = c->streams.find(sid);
    if (it == c->streams.end()) {
      lk.unlock();
      dispose_payload(data, rdv);
      return;
    }
    tpr_server_call *call = it->second;
    if (rst) {
      if (call->inline_cb) {
        c->streams.erase(it);
        lk.unlock();
        delete call;
      } else {
        call->cancelled = true;
        lk.unlock();
        c->cv.notify_all();
      }
      return;
    }
    if (call->inline_cb) {
      lk.unlock();
      int code = 0;
      if (data != nullptr) {
        // the cb borrows the buffer (region or malloc) for the call only
        code = call->inline_cb(call, data, len, call->inline_ud);
        dispose_payload(data, rdv);
      }
      if (code < 0) code = 13;
      if (code != 0 || (flags & kFlagEndStream)) {
        {
          std::lock_guard<std::mutex> lk2(c->mu);
          c->streams.erase(sid);
        }
        c->finish_call_trailers(call, code);
        delete call;
      }
      return;
    }
    if (data != nullptr) {
      OwnedBuf b;
      b.adopt(data, len, rdv);
      call->pending.push_back(std::move(b));
    }
    if (flags & kFlagEndStream) call->half_closed = true;
    lk.unlock();
    c->cv.notify_all();
  }

  void delivery_loop() {
    for (;;) {
      DeliveryItem item;
      {
        std::unique_lock<std::mutex> lk(dq_mu);
        dq_cv.wait(lk, [&] { return dq_stop || !dq.empty(); });
        if (dq.empty()) return;  // stop requested and fully drained
        item = dq.front();
        dq.pop_front();
        size_t depth = dq.size();
        tpr_obs::metric_store(tpr_obs::kMetDlvDepth, depth);
        if (dlv_stalled && depth <= kDlvLowWater) {
          dlv_stalled = false;
          TPR_OBS(tpr_obs::kEvDlvStallEnd, otag_dlv, depth, 0);
        }
      }
      deliver_msg(item.c, item.sid, item.flags, item.data, item.len,
                  item.rdv, item.rst);
      tpr_obs::metric_add(tpr_obs::kMetDlvDrained);
      item.c->delivery_refs.fetch_sub(1);
    }
  }

  static int poller_count_from_env() {
    const char *v = getenv("TPURPC_SERVER_POLLERS");
    if (!v) v = getenv("GRPC_RDMA_POLLER_THREAD_NUM");
    int n = v ? atoi(v) : 1;
    if (n < 1) n = 1;
    if (n > 64) n = 64;
    return n;
  }

  void run_handler(Conn *c, tpr_server_call *call) {
    auto it = handlers.find(call->method);
    int code;
    if (it != handlers.end()) {
      code = it->second.first(call, it->second.second);
    } else if (default_handler != nullptr) {
      code = default_handler(call, default_ud);
    } else {
      code = 12;  // UNIMPLEMENTED
      call->details = "unknown method " + call->method;
    }
    bool was_cancelled;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      was_cancelled = call->cancelled;
      c->streams.erase(call->stream_id);
    }
    if (!was_cancelled) c->finish_call_trailers(call, code);
    delete call;
    c->handler_threads.fetch_sub(1);
  }

  // Protocol sniff + preface, mirroring the Python listener (peek_protocol,
  // endpoint.py): ring clients open with the 4-byte TRB1 bootstrap magic;
  // plain framing clients send the 8-byte TPURPC preface. Runs BLOCKING on
  // the short-lived bootstrap thread (bounded by the client's handshake).
  // `preread` replays sniff bytes an adopting caller already consumed.
  bool accept_preface(Conn *c, const uint8_t *preread, size_t preread_len) {
    char magic[8];
    size_t have = preread_len < 4 ? preread_len : 4;
    if (have) memcpy(magic, preread, have);
    if (have < 4 && !fd_read_exact(c->fd, magic + have, 4 - have))
      return false;
    if (memcmp(magic, "TRB1", 4) == 0) {
      auto *rt = new tpr_ring::RingTransport();
      std::string err;
      if (!rt->bootstrap(c->fd, tpr_wire::ring_size_from_env(),
                         /*preread_magic=*/true, &err)) {
        fprintf(stderr, "tpurpc server: ring bootstrap failed: %s\n",
                err.c_str());
        rt->close();
        delete rt;
        return false;
      }
      c->ring = rt;
      // the framing preface now rides the ring byte stream
      return c->read_exact(magic, 8) && memcmp(magic, kMagic, 8) == 0;
    }
    return fd_read_exact(c->fd, magic + 4, 4) &&
           memcmp(magic, kMagic, 8) == 0;
  }

  // Dispatch one complete frame for `c`. Mirrors the pre-rework
  // serve_conn body; returns false when the connection must end.
  bool on_frame(Conn *c, uint8_t type, uint8_t flags, uint32_t sid,
                std::vector<uint8_t> &payload) {
    if (type >= kRdvOffer && type <= kCtrlKick) {
      // rendezvous/ctrl control ladder: the link consumes these (framed
      // fallback ops, or a kick for our parked ring)
      if (c->link) c->link->on_frame(type, sid, payload.data(),
                                     payload.size());
      return true;
    }
    if (type == kPing) {
      // capability hello rides the PING payload; the echo below doubles
      // as the hello ack either way
      if (c->link) c->link->maybe_hello(payload.data(), payload.size());
      c->send_frame(kPong, 0, 0, payload.data(), payload.size());
      return true;
    }
    if (type == kMessage && c->link && c->link->negotiated.load()) {
      // framed message bytes on a rendezvous-capable conn = host landing
      // copies the ladder did NOT absorb (the ledger the smoke checks)
      tpr_rdv::count(tpr_rdv::kCtrHostCopyBytes, payload.size());
    }
    if (type == kHeaders) {
      std::vector<std::pair<std::string, std::string>> md;
      if (!decode_metadata(payload.data(), payload.size(), &md)) return false;
      auto *call = new tpr_server_call();
      call->conn = c;
      call->stream_id = sid;
      for (auto &kv : md) {
        if (kv.first == ":path") {
          call->method = kv.second;
        } else if (kv.first == ":timeout-us") {
          call->deadline_us =
              std::chrono::duration_cast<std::chrono::microseconds>(
                  Clock::now().time_since_epoch()).count() +
              atoll(kv.second.c_str());
        } else {
          call->md.emplace_back(kv.first, kv.second);
        }
      }
      bool duplicate;
      {
        std::lock_guard<std::mutex> lk(c->mu);
        duplicate = c->streams.count(sid) != 0;
        if (!duplicate) c->streams[sid] = call;
      }
      if (duplicate) {
        // duplicate HEADERS on an active sid: protocol violation —
        // overwriting would orphan one call's frame routing forever
        c->send_trailers(sid, 13, "duplicate stream id");  // INTERNAL
        delete call;
        return true;
      }
      auto cb_it = cb_handlers.find(call->method);
      if (cb_it != cb_handlers.end()) {
        // callback API: no thread — messages dispatch inline below
        call->inline_cb = cb_it->second.first;
        call->inline_ud = cb_it->second.second;
        if (flags & kFlagEndStream) {  // empty call: trailers now
          {
            std::lock_guard<std::mutex> lk2(c->mu);
            c->streams.erase(sid);
          }
          c->finish_call_trailers(call, 0);
          delete call;
        }
        return true;
      }
      c->handler_threads.fetch_add(1);
      std::thread([this, c, call] { run_handler(c, call); }).detach();
      return true;
    }
    // frame for an existing stream
    if (type == kMessage && (flags & kFlagCompressed)) {
      // loud protocol rejection: this loop has no decompressor, and
      // delivering gzip bytes as the message would corrupt the app
      std::unique_lock<std::mutex> lk(c->mu);
      auto it = c->streams.find(sid);
      if (it != c->streams.end()) {
        tpr_server_call *call = it->second;
        // Erase the stream NOW in both branches: a fragmented compressed
        // message delivers kFlagCompressed on every fragment, and later
        // fragments must fall into the finished/unknown drop instead of
        // re-sending these trailers. The details text must keep
        // "compressed messages unsupported" as a substring — the Python
        // channel's compression negotiation keys on it
        // (tpurpc/rpc/frame.py COMPRESSED_UNSUPPORTED_SENTINEL).
        c->streams.erase(it);
        if (call->inline_cb) {
          lk.unlock();
          c->send_trailers(sid, 12 /*UNIMPLEMENTED*/,
                           "compressed messages unsupported here");
          delete call;
        } else {
          call->cancelled = true;  // handler exits; run_handler frees
          lk.unlock();
          c->send_trailers(sid, 12 /*UNIMPLEMENTED*/,
                           "compressed messages unsupported here");
          c->cv.notify_all();
        }
      }
      return true;
    }
    std::unique_lock<std::mutex> lk(c->mu);
    auto it = c->streams.find(sid);
    if (it == c->streams.end()) return true;  // finished/unknown: drop
    tpr_server_call *call = it->second;
    if (delivery_on.load() && c->link && c->link->negotiated.load() &&
        (type == kMessage || type == kRst)) {
      // Shard routing: on negotiated conns the poller only LANDS bytes —
      // completed messages, half-closes and RSTs flow through the delivery
      // FIFO, which is also where rendezvous completions surface, so the
      // two kinds of message stay in their arrival order and no inline cb
      // ever runs concurrently on two threads for one call. (The fragment
      // accumulator stays poller-owned; touching it under c->mu here
      // excludes the shard's erase-then-delete.)
      if (type == kRst) {
        lk.unlock();
        enqueue_delivery(c, sid, flags, nullptr, 0, false, /*rst=*/true);
        return true;
      }
      const bool has_payload = !(flags & kFlagNoMessage);
      const bool complete = has_payload && !(flags & kFlagMore);
      uint8_t *buf = nullptr;
      size_t blen = 0;
      bool have_msg = false;
      if (has_payload) {
        if (complete && call->partial.len == 0) {
          blen = payload.size();
          buf = static_cast<uint8_t *>(malloc(blen ? blen : 1));
          if (buf == nullptr) abort();  // OOM: accumulator path's fate too
          if (blen) memcpy(buf, payload.data(), blen);
          have_msg = true;
        } else {
          call->partial.append(payload.data(), payload.size());
          if (complete) {
            buf = call->partial.release(&blen);
            have_msg = true;
          }
        }
      }
      lk.unlock();
      if (have_msg)
        enqueue_delivery(c, sid, flags, buf, blen, /*rdv=*/false);
      else if (flags & kFlagEndStream)  // pure half-close marker
        enqueue_delivery(c, sid, flags, nullptr, 0, /*rdv=*/false);
      return true;
    }
    if (call->inline_cb) {
      // reactor path: complete messages run the cb ON THIS THREAD;
      // teardown is immediate at RST/half-close/nonzero-return. Only the
      // poller touches inline calls, so the lock is released first.
      lk.unlock();
      bool finished = false;
      bool rst = false;
      int code = 0;
      if (type == kRst) {
        finished = rst = true;  // cancelled: client left, no trailers
      } else if (type == kMessage) {
        const bool has_payload = !(flags & kFlagNoMessage);
        const bool complete = has_payload && !(flags & kFlagMore);
        if (complete && call->partial.len == 0) {
          // common case: whole message in one frame — feed the cb the
          // frame buffer directly, no accumulator alloc/copy
          code = call->inline_cb(call, payload.data(), payload.size(),
                                 call->inline_ud);
        } else {
          if (has_payload)
            call->partial.append(payload.data(), payload.size());
          if (complete) {
            code = call->inline_cb(call, call->partial.p,
                                   call->partial.len, call->inline_ud);
            call->partial.reset();
          }
        }
        // negative returns are app errors, not a protocol escape hatch:
        // map them to INTERNAL so the client always gets trailers
        if (code < 0) code = 13;
        if (code != 0 || (flags & kFlagEndStream)) finished = true;
      }
      if (finished) {
        {
          std::lock_guard<std::mutex> lk2(c->mu);
          c->streams.erase(sid);
        }
        if (!rst) c->finish_call_trailers(call, code);
        delete call;
      }
      return true;
    }
    if (type == kRst) {
      call->cancelled = true;
    } else if (type == kMessage) {
      if (!(flags & kFlagNoMessage))
        call->partial.append(payload.data(), payload.size());
      if (!(flags & kFlagMore) && !(flags & kFlagNoMessage))
        call->pending.push_back(std::move(call->partial));
      if (flags & kFlagEndStream) call->half_closed = true;
    }
    lk.unlock();
    c->cv.notify_all();
    return true;
  }

  // Pump complete frames currently available on `c` (nonblocking), up to
  // a per-event budget so one saturating sender cannot starve the other
  // connections sharing this poller thread (fairness; the reference's
  // Poller round-robins its slot array for the same reason,
  // poller.cc:52-106). Returns: -1 connection over, 0 drained dry,
  // 1 budget exhausted with data still pending (caller must re-pump —
  // the tokens that announced the remaining frames were already drained,
  // so no further epoll event is guaranteed).
  int pump_conn(Conn *c) {
    int budget = 256;
    while (true) {
      uint8_t *dst;
      size_t want;
      if (!c->in_payload) {
        dst = c->hdr + c->got;
        want = sizeof c->hdr - c->got;
      } else {
        dst = c->payload.data() + c->got;
        want = c->f_len - c->got;
      }
      if (want) {
        ssize_t n = c->read_some(dst, want);
        if (n < 0) return -1;
        if (n == 0) return 0;  // dry: wait for the next event
        c->got += static_cast<size_t>(n);
        if (c->got < (c->in_payload ? c->f_len : sizeof c->hdr)) continue;
      }
      if (!c->in_payload) {
        // header complete: parse (t_finish_frame's header layout)
        c->f_type = c->hdr[0];
        c->f_flags = c->hdr[1];
        c->f_sid = get_u32(c->hdr + 2);
        c->f_len = get_u32(c->hdr + 6);
        if (c->f_len > kMaxFramePayload + 65536) return -1;
        c->payload.resize(c->f_len);
        c->in_payload = true;
        c->got = 0;
        if (c->f_len != 0) continue;  // go read the payload bytes
      }
      // frame complete
      c->in_payload = false;
      c->got = 0;
      // ctrl-ring records ordered BEFORE this frame (frame_seq gate)
      // drain first — the Python reader's pre-commit drain; this is what
      // makes ring-borne COMPLETEs land before the TRAILERS behind them
      if (c->link) c->link->ctrl_drain();
      bool frame_ok =
          on_frame(c, c->f_type, c->f_flags, c->f_sid, c->payload);
      if (c->link) {
        c->link->frames_dispatched.fetch_add(1, std::memory_order_release);
        // re-drain now that the count covers this frame: a record gated
        // on it deferred above and would otherwise strand until the next
        // frame (the defer-then-block lost wakeup)
        c->link->ctrl_drain();
      }
      if (!frame_ok) return -1;
      if (--budget == 0) return 1;
    }
  }

  // Connection teardown (poller thread, or destroy): fail streams, wake
  // handlers. The Conn itself is freed by reap once handler threads drain.
  void finish_conn(Conn *c) {
    if (c->finished.exchange(true)) return;
    if (c->otag_conn) {  // the exchange above makes this once-only
      TPR_OBS(tpr_obs::kEvConnDead, c->otag_conn, 0, 0);
      tpr_obs::metric_add(tpr_obs::kMetConnDown);
    }
    // discard-quarantine claimed regions, wake claim waiters (handler
    // threads blocked in a rendezvous claim exit via the framed-fallback
    // path, whose send then fails cleanly on the closed fd)
    if (c->link) c->link->close();
    {
      std::lock_guard<std::mutex> lk(c->mu);
      for (auto &kv : c->streams) kv.second->cancelled = true;
    }
    c->cv.notify_all();
    c->close_fd();
    // Inline (callback-API) calls have no handler thread to free them:
    // whatever still sits in the map with no handler owner is reaped here.
    // Handler-API calls are freed by run_handler (which erases them from
    // the map first), so anything left in the map after handlers DRAIN is
    // poller-owned. With live handler threads, leave the map alone — the
    // reap path frees stragglers once handler_threads hits zero.
    if (c->handler_threads.load() == 0 && c->delivery_refs.load() == 0) {
      std::lock_guard<std::mutex> lk(c->mu);
      for (auto &kv : c->streams) delete kv.second;
      c->streams.clear();
    }
    c->alive.store(false);
  }

  void reap_dead_conns() {
    std::lock_guard<std::mutex> lk(conns_mu);
    for (auto it = conns.begin(); it != conns.end();) {
      Conn *c = *it;
      if (c->reapable.load() && c->handler_threads.load() == 0 &&
          c->delivery_refs.load() == 0) {
        {
          std::lock_guard<std::mutex> lk2(c->mu);
          for (auto &kv : c->streams) delete kv.second;
          c->streams.clear();
        }
        delete c;
        it = conns.erase(it);
      } else {
        ++it;
      }
    }
  }

  // Bootstrap (sniff + optional ring handshake) then hand to a poller.
  void bootstrap_conn(Conn *c, std::vector<uint8_t> preread) {
    bool ok = accept_preface(c, preread.data(), preread.size());
    if (!ok || !running.load()) {
      finish_conn(c);
      c->reapable.store(true);  // never reached a poller: no stale events
    } else {
      // rendezvous/ctrl-ring link: wired before the conn can dispatch a
      // frame. The hello PING (capability + our ring descriptor) goes out
      // right after the preface; an un-negotiated peer just echoes PONG
      // and stays on the framed path, byte-identical to before.
      c->link = new tpr_rdv::Link("srv");
      c->link->send_frame = [c](uint8_t type, uint32_t sid,
                                const std::string &p) {
        return c->send_frame(type, 0, sid, p.data(), p.size());
      };
      c->link->deliver = [this, c](uint32_t sid, uint8_t flags,
                                   uint8_t *data, size_t len) {
        if (delivery_on.load())
          enqueue_delivery(c, sid, flags, data, len, /*rdv=*/true);
        else
          deliver_msg(c, sid, flags, data, len, /*rdv=*/true, false);
      };
      c->link->wake = [c] { c->cv.notify_all(); };
      if (tpr_obs::enabled()) {
        static std::atomic<uint64_t> g_conn_ord{1};
        char tb[44];
        snprintf(tb, sizeof tb, "nconn:srv#%llu",
                 (unsigned long long)g_conn_ord.fetch_add(1));
        c->otag_conn = tpr_obs::tag_for(tb);
        TPR_OBS(tpr_obs::kEvConnConnect, c->otag_conn, 0, 0);
        tpr_obs::metric_add(tpr_obs::kMetConnUp);
      }
      std::string hello = c->link->hello_payload();
      c->send_frame(kPing, 0, 0, hello.data(), hello.size());
      Poller *p = pollers[next_poller.fetch_add(1) % pollers.size()];
      c->poller = p;
      p->add(c);
    }
    bootstrap_threads.fetch_sub(1);
  }

  void start_conn(int fd, const uint8_t *preread, size_t preread_len) {
    // Bound growth for BOTH intake paths: adopted fds never pass through
    // accept_loop, and without this an adoption-churn workload accumulates
    // every dead conn's ring mappings (measured: ~1 GB RSS over 240
    // churned ring connections).
    reap_dead_conns();
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    auto *c = new Conn();
    c->fd = fd;
    {
      std::lock_guard<std::mutex> lk(conns_mu);
      conns.push_back(c);
    }
    bootstrap_threads.fetch_add(1);
    std::vector<uint8_t> pre(preread, preread + preread_len);
    std::thread([this, c, pre = std::move(pre)]() mutable {
      bootstrap_conn(c, std::move(pre));
    }).detach();
  }

  void accept_loop() {
    while (running.load()) {
      struct sockaddr_in peer {};
      socklen_t plen = sizeof peer;
      int fd = ::accept(listen_fd, reinterpret_cast<sockaddr *>(&peer), &plen);
      if (fd < 0) {
        if (errno == EINTR) continue;
        return;  // listener closed
      }
      start_conn(fd, nullptr, 0);  // start_conn reaps (both intake paths)
    }
  }
};

namespace {

void Poller::loop() {
  constexpr int kMaxEvents = 64;
  struct epoll_event evs[kMaxEvents];
  // Conns whose last pump hit the fairness budget with data still pending:
  // re-pumped every iteration (their announcing tokens are already
  // consumed, so no further epoll event is guaranteed). While any are hot
  // the epoll_wait runs nonblocking so fresh events interleave fairly.
  std::vector<Conn *> hot;
  // every conn this poller serves (for the ctrl-ring hot-poll sweep)
  std::vector<Conn *> managed;
  while (running.load()) {
    // drain-EWMA hot/cold (read_frame_polled's discipline): while any
    // link's ring is hot, poll on ~1 ms slices instead of the 200 ms
    // block — steady-state bulk then needs zero fd kicks
    bool ctrl_hot_any = false;
    for (Conn *mc : managed) {
      if (!mc->finished.load() && mc->link && mc->link->ctrl_hot()) {
        ctrl_hot_any = true;
        break;
      }
    }
    int n = ::epoll_wait(epfd, evs, kMaxEvents,
                         !hot.empty() ? 0 : (ctrl_hot_any ? 1 : 200));
    if (!running.load()) return;
    // adopt pending conns FIRST, with an unconditional initial pump: ring
    // bytes that landed during bootstrap may carry no further token
    std::vector<Conn *> fresh;
    {
      std::lock_guard<std::mutex> lk(add_mu);
      fresh.swap(pending_add);
    }
    std::vector<Conn *> finished_this_batch;
    auto end_conn = [&](Conn *c) {
      ::epoll_ctl(epfd, EPOLL_CTL_DEL, c->event_fd(), nullptr);
      srv->finish_conn(c);
      finished_this_batch.push_back(c);
    };
    auto after_pump = [&](Conn *c, int r) {
      if (r < 0) {
        end_conn(c);
      } else if (r == 1) {
        hot.push_back(c);  // budget hit: data pending, owe a re-pump
      }
    };
    for (Conn *c : fresh) {
      struct epoll_event ev = {};
      ev.events = EPOLLIN;
      ev.data.ptr = c;
      if (::epoll_ctl(epfd, EPOLL_CTL_ADD, c->event_fd(), &ev) != 0) {
        end_conn(c);
        continue;
      }
      managed.push_back(c);
      // this thread is the conn's frame-dispatch hart: it must never
      // block in a claim wait (the claim it waits for dispatches here)
      if (c->link) c->link->set_dispatch_thread();
      after_pump(c, srv->pump_conn(c));
    }
    std::vector<Conn *> rehot;
    rehot.swap(hot);
    for (Conn *c : rehot) {
      if (c->finished.load()) continue;
      after_pump(c, srv->pump_conn(c));
    }
    for (int i = 0; i < n; ++i) {
      Conn *c = static_cast<Conn *>(evs[i].data.ptr);
      if (c == nullptr) {  // wake pipe (nonblocking): drain
        char buf[64];
        while (::read(wake_r, buf, sizeof buf) > 0) {
        }
        continue;
      }
      if (c->finished.load()) continue;  // stale event post-teardown
      if (c->ring) {
        // tokens first (level-triggered fd would re-fire otherwise),
        // then drain the ring. A closed notify channel still gets its
        // ring remnants served before teardown (the peer's final frames
        // race its close, exactly like the old blocking path).
        int t = c->ring->drain_tokens();
        int r = srv->pump_conn(c);
        if (t < 0 && r != 1) r = -1;  // keep pumping remnants while hot
        after_pump(c, r);
      } else {
        after_pump(c, srv->pump_conn(c));
      }
    }
    // ctrl-ring sweep: drain hot links; an empty probe decays the EWMA,
    // and a link that just went cold PARKS (parked=1 + one mandatory
    // re-drain, closing the lost-wakeup race — the producer reads parked
    // strictly after its stamp store). Kicks then wake us via the fd.
    for (Conn *c : managed) {
      if (c->finished.load() || !c->link || !c->link->ctrl_rx_ready())
        continue;
      if (c->link->ctrl_hot() && c->link->ctrl_drain() == 0) {
        c->link->ctrl_decay();
        if (!c->link->ctrl_hot()) c->link->ctrl_park();
      }
    }
    // A conn can land in `hot` (budget hit) and THEN be finished by a later
    // epoll event in the same batch; it stays in `hot` across iterations, so
    // if the reaper freed it between batches the next rehot pass would read
    // freed memory. Purge finished conns from `hot` before making anything
    // reapable — only then is no poller-local pointer left to them.
    hot.erase(std::remove_if(hot.begin(), hot.end(),
                             [](Conn *c) { return c->finished.load(); }),
              hot.end());
    managed.erase(std::remove_if(managed.begin(), managed.end(),
                                 [](Conn *c) { return c->finished.load(); }),
                  managed.end());
    // only AFTER the batch (no stale event can reference them) may the
    // reaper free these conns
    for (Conn *c : finished_this_batch) c->reapable.store(true);
  }
}

}  // namespace

// ---------------------------------------------------------------------------

extern "C" {

tpr_server *tpr_server_create(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr {};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof addr) != 0 ||
      ::listen(fd, 512) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  auto *s = new tpr_server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  return s;
}

int tpr_server_port(tpr_server *s) { return s->port; }

void tpr_server_register(tpr_server *s, const char *method, tpr_handler_fn fn,
                         void *ud) {
  s->handlers[method] = {fn, ud};
}

void tpr_server_register_callback(tpr_server *s, const char *method,
                                  tpr_msg_cb on_msg, void *ud) {
  s->cb_handlers[method] = {on_msg, ud};
}

void tpr_server_register_default(tpr_server *s, tpr_handler_fn fn, void *ud) {
  s->default_handler = fn;
  s->default_ud = ud;
}

// GRPC_RDMA_AFFINITY / TPURPC_AFFINITY: pin poller i to core i % ncores.
// The reference PARSES this knob but never consumes it (rdma_utils.h:72-73
// is_affinity has zero call sites); here it actually pins — on multicore
// hosts a wandering poller pays cache/TLB refills every migration, the
// cost the round-5 scalability profile measured as per-RPC cycle growth.
static bool affinity_from_env() {
  const char *v = getenv("TPURPC_AFFINITY");
  if (!v) v = getenv("GRPC_RDMA_AFFINITY");
  return v != nullptr && (v[0] == '1' || strcmp(v, "true") == 0);
}

int tpr_server_start(tpr_server *s) {
  s->running.store(true);
  int np = tpr_server::poller_count_from_env();
  bool pin = affinity_from_env();
  // Pin within the process's ALLOWED set, not raw core ids: under a
  // cpuset/taskset restriction (cores 60-63, say) CPU_SET(i % ncores)
  // would target forbidden cores and the knob would silently no-op in
  // exactly the containerized deployments that need it.
  std::vector<int> allowed;
  if (pin) {
    cpu_set_t proc_set;
    CPU_ZERO(&proc_set);
    if (sched_getaffinity(0, sizeof proc_set, &proc_set) == 0) {
      for (int c = 0; c < CPU_SETSIZE; ++c)
        if (CPU_ISSET(c, &proc_set)) allowed.push_back(c);
    }
  }
  for (int i = 0; i < np; ++i) {
    auto *p = new Poller();
    if (!p->init()) {
      delete p;
      return -1;
    }
    p->srv = s;
    p->th = std::thread([p] { p->loop(); });
    if (pin && !allowed.empty()) {
      cpu_set_t set;
      CPU_ZERO(&set);
      CPU_SET(allowed[i % allowed.size()], &set);
      // best effort: a denied setaffinity is not an error
      pthread_setaffinity_np(p->th.native_handle(), sizeof set, &set);
    }
    s->pollers.push_back(p);
  }
  s->delivery_on.store(tpr_server::delivery_from_env());
  if (s->delivery_on.load()) {
    if (tpr_obs::enabled()) s->otag_dlv = tpr_obs::tag_for("ndlv:srv");
    s->delivery_th = std::thread([s] { s->delivery_loop(); });
  }
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return 0;
}

int tpr_server_adopt_fd(tpr_server *s, int fd, const uint8_t *preread,
                        size_t preread_len) {
  if (!s->running.load() || preread_len > 4) return -1;
  s->start_conn(fd, preread, preread_len);
  return 0;
}

void tpr_server_destroy(tpr_server *s) {
  s->running.store(false);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // bootstrap threads hold Conn pointers; their sniffs are bounded (the
  // fd shutdowns below kick any that are mid-handshake)
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    for (Conn *c : s->conns) c->shutdown_fd();
  }
  while (s->bootstrap_threads.load() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  for (Poller *p : s->pollers) {
    p->stop_and_join();
    delete p;
  }
  s->pollers.clear();
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    for (Conn *c : s->conns) {
      s->finish_conn(c);
      while (c->handler_threads.load() > 0)
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }
  // All producers (pollers, handlers) are quiet: drain and stop the
  // delivery shard BEFORE freeing conns — queued items hold raw Conn*.
  if (s->delivery_th.joinable()) {
    {
      std::lock_guard<std::mutex> lk(s->dq_mu);
      s->dq_stop = true;
    }
    s->dq_cv.notify_all();
    s->delivery_th.join();
  }
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    for (Conn *c : s->conns) {
      {
        std::lock_guard<std::mutex> lk2(c->mu);
        for (auto &kv : c->streams) delete kv.second;
        c->streams.clear();
      }
      delete c;
    }
    s->conns.clear();
  }
  delete s;
}

int tpr_srv_recv(tpr_server_call *c, uint8_t **data, size_t *len) {
  Conn *conn = c->conn;
  std::unique_lock<std::mutex> lk(conn->mu);
  while (true) {
    if (!c->pending.empty()) {
      // zero-copy handoff: the accumulator is malloc-backed from the
      // start, so the handler takes the buffer itself (frees with
      // tpr_srv_buf_free == free(), the unchanged contract)
      *data = c->pending.front().release(len);
      c->pending.pop_front();
      return 1;
    }
    if (c->cancelled) return -1;
    if (c->half_closed) return 0;
    conn->cv.wait(lk);
  }
}

static void flush_initial_md(tpr_server_call *c) {
  if (c->initial_md_sent) return;
  c->initial_md_sent = true;
  if (c->initial_md.empty()) return;
  std::string payload = encode_metadata(c->initial_md);
  c->conn->send_frame(kHeaders, 0, c->stream_id, payload.data(),
                      payload.size());
}

int tpr_srv_send(tpr_server_call *c, const uint8_t *data, size_t len) {
  flush_initial_md(c);
  // Bulk ladder: eligible payloads on a negotiated link move by one
  // one-sided write into a claimed landing region + one COMPLETE record —
  // zero framed MESSAGE bytes. ANY failure returns false and the framed
  // loop below carries the message instead (fallback, never a hang).
  tpr_rdv::Link *link = c->conn->link;
  if (link && link->eligible(len) &&
      link->send_message(c->stream_id, 0, data, len))
    return 0;
  size_t off = 0;
  do {
    size_t n = len - off;
    bool last = n <= kMaxFramePayload;
    if (!last) n = kMaxFramePayload;
    uint8_t flags = last ? 0 : kFlagMore;
    if (!c->conn->send_frame(kMessage, flags, c->stream_id, data + off, n))
      return -1;
    off += n;
  } while (off < len);
  return 0;
}

const char *tpr_srv_method(tpr_server_call *c) { return c->method.c_str(); }

int64_t tpr_srv_deadline_us(tpr_server_call *c) {
  if (c->deadline_us == INT64_MAX) return INT64_MAX;
  int64_t now = std::chrono::duration_cast<std::chrono::microseconds>(
                    Clock::now().time_since_epoch()).count();
  int64_t left = c->deadline_us - now;
  return left > 0 ? left : 0;
}

void tpr_srv_set_details(tpr_server_call *c, const char *details) {
  c->details = details ? details : "";
}

size_t tpr_srv_metadata_count(tpr_server_call *c) { return c->md.size(); }

int tpr_srv_metadata_get(tpr_server_call *c, size_t i, const char **key,
                         const char **val) {
  if (i >= c->md.size()) return -1;
  *key = c->md[i].first.c_str();
  *val = c->md[i].second.c_str();
  return 0;
}

void tpr_srv_send_initial_md(tpr_server_call *c, const char *key,
                             const char *val) {
  if (!c->initial_md_sent)
    c->initial_md.emplace_back(key ? key : "", val ? val : "");
}

void tpr_srv_add_trailing_md(tpr_server_call *c, const char *key,
                             const char *val) {
  c->trailing_md.emplace_back(key ? key : "", val ? val : "");
}

int tpr_srv_cancelled(tpr_server_call *c) {
  std::lock_guard<std::mutex> lk(c->conn->mu);
  return c->cancelled ? 1 : 0;
}

void tpr_srv_buf_free(uint8_t *data) {
  // a delivered rendezvous region settles (doorbell/recycle); everything
  // else keeps the original free() contract
  if (!tpr_rdv::settle(data)) free(data);
}

}  // extern "C"
