// Shared wire-framing helpers for the native client and server
// (tpurpc_client.cc / tpurpc_server.cc). The authoritative format doc is
// tpurpc/rpc/frame.py: 8-byte preface "TPURPC\x01\x00", little-endian
// frames [u8 type][u8 flags][u32 stream_id][u32 length][payload], metadata
// as u16 count + (u16 klen, key, u32 vlen, value) entries.
#ifndef TPURPC_FRAMING_COMMON_H
#define TPURPC_FRAMING_COMMON_H

#include <errno.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>

#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace tpr_wire {

constexpr uint8_t kHeaders = 1, kMessage = 2, kTrailers = 3, kRst = 4,
                  kPing = 5, kPong = 6, kGoaway = 7;
constexpr uint8_t kFlagEndStream = 0x01, kFlagMore = 0x02,
                  kFlagNoMessage = 0x04;
constexpr size_t kMaxFramePayload = 1u << 20;
inline const char kMagic[] = "TPURPC\x01\x00";  // 8 bytes incl trailing NUL

inline void put_u16(std::string &out, uint16_t v) {
  out.push_back(static_cast<char>(v & 0xff));
  out.push_back(static_cast<char>(v >> 8));
}
inline void put_u32(std::string &out, uint32_t v) {
  for (int i = 0; i < 4; i++)
    out.push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}
inline uint16_t get_u16(const uint8_t *p) {
  return static_cast<uint16_t>(p[0] | (p[1] << 8));
}
inline uint32_t get_u32(const uint8_t *p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | (uint32_t(p[3]) << 24);
}

inline std::string encode_metadata(
    const std::vector<std::pair<std::string, std::string>> &md) {
  std::string out;
  put_u16(out, static_cast<uint16_t>(md.size()));
  for (const auto &kv : md) {
    put_u16(out, static_cast<uint16_t>(kv.first.size()));
    out += kv.first;
    put_u32(out, static_cast<uint32_t>(kv.second.size()));
    out += kv.second;
  }
  return out;
}

inline bool decode_metadata(
    const uint8_t *buf, size_t len,
    std::vector<std::pair<std::string, std::string>> *out) {
  if (len < 2) return false;
  size_t off = 2;
  uint16_t count = get_u16(buf);
  for (uint16_t i = 0; i < count; i++) {
    if (off + 2 > len) return false;
    uint16_t klen = get_u16(buf + off);
    off += 2;
    if (off + klen + 4 > len) return false;
    std::string key(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen = get_u32(buf + off);
    off += 4;
    if (off + vlen > len) return false;
    out->emplace_back(
        std::move(key),
        std::string(reinterpret_cast<const char *>(buf + off), vlen));
    off += vlen;
  }
  return true;
}

inline bool fd_write_all(int fd, const void *buf, size_t len) {
  const char *p = static_cast<const char *>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

inline bool fd_read_exact(int fd, void *buf, size_t len) {
  char *p = static_cast<char *>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Serialized whole-frame write (the FrameWriter-analog lock lives with the
// caller's mutex).
inline bool fd_send_frame_locked(int fd, uint8_t type, uint8_t flags,
                                 uint32_t sid, const void *payload,
                                 size_t len) {
  std::string hdr;
  hdr.push_back(static_cast<char>(type));
  hdr.push_back(static_cast<char>(flags));
  put_u32(hdr, sid);
  put_u32(hdr, static_cast<uint32_t>(len));
  return fd_write_all(fd, hdr.data(), hdr.size()) &&
         (len == 0 || fd_write_all(fd, payload, len));
}

// Read one frame header+payload; false on EOF/error/insane length.
inline bool fd_read_frame(int fd, uint8_t *type, uint8_t *flags,
                          uint32_t *sid, std::vector<uint8_t> *payload) {
  uint8_t hdr[10];
  if (!fd_read_exact(fd, hdr, sizeof hdr)) return false;
  *type = hdr[0];
  *flags = hdr[1];
  *sid = get_u32(hdr + 2);
  uint32_t len = get_u32(hdr + 6);
  if (len > kMaxFramePayload + 65536) return false;
  payload->resize(len);
  return len == 0 || fd_read_exact(fd, payload->data(), len);
}

}  // namespace tpr_wire

#endif  // TPURPC_FRAMING_COMMON_H
