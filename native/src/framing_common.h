// Shared wire-framing helpers for the native client and server
// (tpurpc_client.cc / tpurpc_server.cc). The authoritative format doc is
// tpurpc/rpc/frame.py: 8-byte preface "TPURPC\x01\x00", little-endian
// frames [u8 type][u8 flags][u32 stream_id][u32 length][payload], metadata
// as u16 count + (u16 klen, key, u32 vlen, value) entries.
#ifndef TPURPC_FRAMING_COMMON_H
#define TPURPC_FRAMING_COMMON_H

#include <errno.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>

#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace tpr_wire {

constexpr uint8_t kHeaders = 1, kMessage = 2, kTrailers = 3, kRst = 4,
                  kPing = 5, kPong = 6, kGoaway = 7,
                  // rendezvous control ladder (frame.py RDV_*): frame type
                  // = canonical op + 7 (OP_OFFER=1 .. OP_RELEASE=4)
                  kRdvOffer = 8, kRdvClaim = 9, kRdvComplete = 10,
                  kRdvRelease = 11,
                  // one framed wakeup for a parked ctrl-ring consumer; the
                  // fd readiness IS the wake — the frame body is ignored
                  kCtrlKick = 12;
constexpr uint8_t kFlagEndStream = 0x01, kFlagMore = 0x02,
                  kFlagNoMessage = 0x04,
                  // gzip-compressed message (Python peers only): the native
                  // loop does not link a decompressor, so receivers REJECT
                  // the flag loudly instead of delivering garbled bytes
                  kFlagCompressed = 0x08,
                  // on kRst only: the stream was REFUSED at admission — no
                  // handler ran, the caller may replay on a fresh connection
                  // (h2 REFUSED_STREAM semantics; the machine-readable form
                  // of the old "connection draining" detail wording —
                  // frame.py FLAG_REFUSED is the Python mirror)
                  kFlagRefused = 0x10;
constexpr size_t kMaxFramePayload = 1u << 20;
// Unary requests at or below this ship HEADERS+MESSAGE as ONE buffered
// write (one syscall / ring message); larger ones take the fragmenting
// send path (a single MESSAGE frame above kMaxFramePayload is a framing
// violation that kills the connection). Shared by the blocking and CQ
// unary fast paths so the cutoff can't drift between them.
constexpr size_t kSmallUnaryMax = 64u << 10;
inline const char kMagic[] = "TPURPC\x01\x00";  // 8 bytes incl trailing NUL

inline void put_u16(std::string &out, uint16_t v) {
  out.push_back(static_cast<char>(v & 0xff));
  out.push_back(static_cast<char>(v >> 8));
}
inline void put_u32(std::string &out, uint32_t v) {
  for (int i = 0; i < 4; i++)
    out.push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}
inline uint16_t get_u16(const uint8_t *p) {
  return static_cast<uint16_t>(p[0] | (p[1] << 8));
}
inline uint32_t get_u32(const uint8_t *p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | (uint32_t(p[3]) << 24);
}

inline std::string encode_metadata(
    const std::vector<std::pair<std::string, std::string>> &md) {
  std::string out;
  put_u16(out, static_cast<uint16_t>(md.size()));
  for (const auto &kv : md) {
    put_u16(out, static_cast<uint16_t>(kv.first.size()));
    out += kv.first;
    put_u32(out, static_cast<uint32_t>(kv.second.size()));
    out += kv.second;
  }
  return out;
}

inline bool decode_metadata(
    const uint8_t *buf, size_t len,
    std::vector<std::pair<std::string, std::string>> *out) {
  if (len < 2) return false;
  size_t off = 2;
  uint16_t count = get_u16(buf);
  for (uint16_t i = 0; i < count; i++) {
    if (off + 2 > len) return false;
    uint16_t klen = get_u16(buf + off);
    off += 2;
    if (off + klen + 4 > len) return false;
    std::string key(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen = get_u32(buf + off);
    off += 4;
    if (off + vlen > len) return false;
    out->emplace_back(
        std::move(key),
        std::string(reinterpret_cast<const char *>(buf + off), vlen));
    off += vlen;
  }
  return true;
}

inline bool fd_write_all(int fd, const void *buf, size_t len) {
  const char *p = static_cast<const char *>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

inline bool fd_read_exact(int fd, void *buf, size_t len) {
  char *p = static_cast<char *>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Transport-generic frame IO: T needs write_all(ptr,len)/read_exact(ptr,len)
// with the usual all-or-nothing contract. This is the seam that lets one
// framing loop ride either a TCP fd or the shm ring transport
// (ring_transport.h) — the grpc_endpoint-vtable idea (endpoint.cc:33-54) at
// native-app scale. (The former fd_-prefixed frame helpers were these exact
// bodies specialized to an fd; callers now go through the templates.)
inline void build_frame_header(std::string &hdr, uint8_t type, uint8_t flags,
                               uint32_t sid, size_t len) {
  hdr.push_back(static_cast<char>(type));
  hdr.push_back(static_cast<char>(flags));
  put_u32(hdr, sid);
  put_u32(hdr, static_cast<uint32_t>(len));
}

template <typename T>
inline bool t_send_frame_locked(T &t, uint8_t type, uint8_t flags,
                                uint32_t sid, const void *payload,
                                size_t len) {
  std::string hdr;
  build_frame_header(hdr, type, flags, sid, len);
  return t.write_all(hdr.data(), hdr.size()) &&
         (len == 0 || t.write_all(payload, len));
}

// Ring-transport specialization: header+payload as one gathered ring
// message, one notify (R = tpr_ring::RingTransport or anything with
// write_gather).
template <typename R>
inline bool ring_send_frame_locked(R &ring, uint8_t type, uint8_t flags,
                                   uint32_t sid, const void *payload,
                                   size_t len) {
  std::string hdr;
  build_frame_header(hdr, type, flags, sid, len);
  return ring.write_gather(hdr.data(), hdr.size(), payload, len);
}

// Parse a 10-byte frame header and read the payload — shared by the
// blocking and deadline-bounded frame readers so the header layout and
// the sanity bound live in exactly one place.
template <typename T>
inline bool t_finish_frame(T &t, const uint8_t hdr[10], uint8_t *type,
                           uint8_t *flags, uint32_t *sid,
                           std::vector<uint8_t> *payload) {
  *type = hdr[0];
  *flags = hdr[1];
  *sid = get_u32(hdr + 2);
  uint32_t len = get_u32(hdr + 6);
  if (len > kMaxFramePayload + 65536) return false;
  payload->resize(len);
  return len == 0 || t.read_exact(payload->data(), len);
}

template <typename T>
inline bool t_read_frame(T &t, uint8_t *type, uint8_t *flags, uint32_t *sid,
                         std::vector<uint8_t> *payload) {
  uint8_t hdr[10];
  if (!t.read_exact(hdr, sizeof hdr)) return false;
  return t_finish_frame(t, hdr, type, flags, sid, payload);
}

// GRPC_PLATFORM_TYPE dispatch for native apps (iomgr_internal.cc:36-61
// analog): any of the ring platforms means "bootstrap the shm ring over
// the connected socket"; TCP (or unset) keeps plain fd framing.
inline bool platform_wants_ring() {
  const char *p = getenv("TPURPC_PLATFORM_TYPE");
  if (!p) p = getenv("GRPC_PLATFORM_TYPE");
  if (!p) return false;
  return strcmp(p, "RDMA_BP") == 0 || strcmp(p, "RDMA_BPEV") == 0 ||
         strcmp(p, "RDMA_EVENT") == 0;
}

inline uint64_t ring_size_from_env() {
  const char *p = getenv("TPURPC_RING_BUFFER_SIZE_KB");
  if (!p) p = getenv("GRPC_RDMA_RING_BUFFER_SIZE_KB");
  uint64_t kb = p ? strtoull(p, nullptr, 10) : 4096;
  if (kb == 0) kb = 4096;
  uint64_t bytes = kb * 1024;
  // power-of-two, >= 4096 (config.py ring_buffer_size rule)
  uint64_t size = 4096;
  while (size < bytes) size <<= 1;
  return size;
}

}  // namespace tpr_wire

#endif  // TPURPC_FRAMING_COMMON_H
