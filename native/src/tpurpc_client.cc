// tpurpc C client implementation — native app API over the tpurpc framing.
//
// Wire format (authoritative doc: tpurpc/rpc/frame.py): 8-byte preface
// "TPURPC\x01\x00", then little-endian frames
//   [u8 type][u8 flags][u32 stream_id][u32 length][payload]
// with the gRPC-subset frame vocabulary (HEADERS/MESSAGE/TRAILERS/RST/
// PING/PONG/GOAWAY) the reference's chttp2 layer provides (frame_*.cc).
// One reader thread per channel demuxes to per-call mailboxes — the
// collapsed analog of grpc's pollset/completion-queue machinery
// (completion_queue.cc:393) for a blocking app API.

#include "../include/tpurpc/client.h"

#include "framing_common.h"
#include "ring_transport.h"
#include "tpr_obs.h"
#include "tpr_rdv.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

using namespace tpr_wire;
using Clock = std::chrono::steady_clock;

// One queued completion. Value type: owns copies of everything it carries,
// so events stay valid after the originating call is destroyed.
struct CqEvent {
  int type = 0;
  void *tag = nullptr;
  int ok = 0;
  bool has_data = false;
  std::string data;
  int status = 0;
  std::string details;
};

// (cq, event) pairs collected — and pushed — under ch->mu, so completions
// reach the queue in the order they were generated (a push after releasing
// ch->mu could interleave with a racing canceller's terminal events,
// delivering a RECV after its call's FINISH). Lock nesting is strictly
// one-way: ch->mu → cq->mu; nothing takes ch->mu while holding cq->mu
// (tpr_cq_next releases cq->mu before its expiry RST).
using CqDeliveries = std::vector<std::pair<tpr_cq *, CqEvent>>;

struct Call {
  uint32_t stream_id = 0;
  tpr_channel *ch = nullptr;
  std::deque<std::string> messages;   // complete reassembled messages
  std::string partial;                // FLAG_MORE fragment accumulator
  bool trailers_seen = false;
  int status_code = TPR_UNKNOWN;
  std::string status_details;
  bool refused = false;  // kRst|kFlagRefused: admission refusal, no handler
  Clock::time_point deadline{};
  bool has_deadline = false;
  bool cancelled = false;
  int internal_users = 0;  // threads inside rst_and_finish_locally's send
  // CQ-async state (tags guarded by ch->mu; cq_pins by cq->mu; `done` is
  // atomic so the cq's deadline scan can read it without ch->mu).
  tpr_cq *cq = nullptr;
  std::deque<void *> recv_tags;
  bool finish_armed = false;
  void *finish_tag = nullptr;
  bool unary_armed = false;
  void *unary_tag = nullptr;
  std::atomic<bool> done{false};
  int cq_pins = 0;  // tpr_cq_next threads holding this call across an expiry
};

}  // namespace

struct tpr_cq {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<CqEvent> q;
  bool shut = false;
  // CQ calls with a deadline, scanned lazily by tpr_cq_next (the puller
  // doubles as the timer thread — grpc's cq-driven timer check shape).
  std::set<tpr_call *> timed_calls;
};

namespace {

// Under ch->mu: match queued messages with pending recv tags, and emit the
// terminal completions once trailers are in. Called at every delivery point
// (reader loop, die(), local RST, op arming).
void drain_cq_locked(Call &c, CqDeliveries *out) {
  if (c.cq == nullptr) return;
  while (!c.recv_tags.empty() && !c.messages.empty()) {
    CqEvent ev;
    ev.type = TPR_EV_RECV;
    ev.tag = c.recv_tags.front();
    ev.ok = 1;
    ev.has_data = true;
    ev.data = std::move(c.messages.front());
    c.messages.pop_front();
    c.recv_tags.pop_front();
    out->emplace_back(c.cq, std::move(ev));
  }
  if (!c.trailers_seen) return;
  c.done.store(true);
  while (!c.recv_tags.empty()) {  // end of stream: ok=0, no message
    CqEvent ev;
    ev.type = TPR_EV_RECV;
    ev.tag = c.recv_tags.front();
    c.recv_tags.pop_front();
    out->emplace_back(c.cq, std::move(ev));
  }
  if (c.finish_armed) {
    CqEvent ev;
    ev.type = TPR_EV_FINISH;
    ev.tag = c.finish_tag;
    ev.ok = 1;
    ev.status = c.status_code;
    ev.details = c.status_details;
    c.finish_armed = false;
    out->emplace_back(c.cq, std::move(ev));
  }
  if (c.unary_armed) {  // response + status in ONE completion
    CqEvent ev;
    ev.type = TPR_EV_FINISH;
    ev.tag = c.unary_tag;
    ev.ok = 1;
    ev.status = c.status_code;
    ev.details = c.status_details;
    if (!c.messages.empty()) {
      ev.has_data = true;
      ev.data = std::move(c.messages.front());
      c.messages.pop_front();
    }
    c.unary_armed = false;
    out->emplace_back(c.cq, std::move(ev));
  }
}

void cq_push(CqDeliveries *evs) {
  // Batch consecutive events for the same cq (the overwhelmingly common
  // case) under one lock acquisition + one notify — the caller holds
  // ch->mu, so per-event churn here would serialize the whole channel.
  size_t i = 0;
  while (i < evs->size()) {
    tpr_cq *cq = (*evs)[i].first;
    {
      std::lock_guard<std::mutex> lk(cq->mu);
      for (; i < evs->size() && (*evs)[i].first == cq; ++i)
        cq->q.push_back(std::move((*evs)[i].second));
    }
    cq->cv.notify_all();
  }
  evs->clear();
}

}  // namespace

struct tpr_call {
  Call c;
};

struct tpr_channel {
  int fd = -1;
  // Ring data plane (GRPC_PLATFORM_TYPE=RDMA_*): frames ride the shm ring;
  // the socket stays as the bootstrap/notify channel inside the transport.
  tpr_ring::RingTransport *ring = nullptr;
  std::mutex write_mu;                 // serializes whole frames (FrameWriter analog)
  std::mutex mu;                       // guards streams / pong / alive
  std::condition_variable cv;          // signaled on any delivery
  std::map<uint32_t, tpr_call *> streams;
  uint32_t next_stream_id = 1;         // odd, client-initiated (h2 convention)
  bool draining = false;               // GOAWAY seen: no new calls (mu)
  std::atomic<bool> alive{true};
  uint64_t pong_count = 0;
  std::thread reader;
  bool inline_read = false;  // no reader thread; waiters pump (ring only)
  bool pumping = false;      // a thread is inside the transport (mu)
  // zero-copy send lease state. write_mu is HELD from a successful
  // tpr_call_send_reserve until commit/abort; lease_active is atomic and
  // lease_owner records the holder so misuse (same-thread re-reserve,
  // commit from a thread that isn't the owner) returns -1 instead of
  // deadlocking on the non-recursive mutex / unlocking a foreign lock.
  std::atomic<bool> lease_active{false};
  std::thread::id lease_owner{};
  uint64_t lease_len = 0;
  // rendezvous + ctrl-ring side of this channel (tpr_rdv.h); armed only if
  // the peer's hello PING negotiates the ladder
  tpr_rdv::Link *link = nullptr;
  // tpurpc-xray conn-lifecycle flight tag (interned once at create);
  // dead_emitted keeps the death edge an EDGE across die()/destructor
  uint16_t otag_conn = 0;
  std::atomic<bool> dead_emitted{false};

  ~tpr_channel() {
    if (otag_conn && !dead_emitted.exchange(true)) {
      TPR_OBS(tpr_obs::kEvConnDead, otag_conn, 1, 0);  // graceful teardown
      tpr_obs::metric_add(tpr_obs::kMetConnDown);
    }
    alive.store(false);
    if (link) link->close();  // wake claim waiters before the reader join
    if (ring) ring->shutdown();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
    if (reader.joinable()) reader.join();
    delete link;  // after the join: the reader drains/dispatches into it
    link = nullptr;
    if (ring) {
      ring->close();
      delete ring;
    }
    if (fd >= 0) ::close(fd);
  }

  bool write_all(const void *buf, size_t len) {
    return ring ? ring->write_all(buf, len)
                : tpr_wire::fd_write_all(fd, buf, len);
  }

  bool send_frame(uint8_t type, uint8_t flags, uint32_t sid,
                  const void *payload, size_t len) {
    std::lock_guard<std::mutex> lk(write_mu);
    if (!alive.load()) return false;
    bool ok = ring  // one gathered ring message + one notify per frame
                  ? ring_send_frame_locked(*ring, type, flags, sid, payload,
                                           len)
                  : t_send_frame_locked(*this, type, flags, sid, payload,
                                        len);
    // EVERY frame actually written counts (ctrl-ring records stamp this
    // value as their ordering gate; an overcount would strand records)
    if (ok && link) link->frames_sent.fetch_add(1, std::memory_order_release);
    return ok;
  }

  bool read_exact(void *buf, size_t len) {
    return ring ? ring->read_exact(buf, len)
                : tpr_wire::fd_read_exact(fd, buf, len);
  }

  void die() {
    if (otag_conn && !dead_emitted.exchange(true)) {
      TPR_OBS(tpr_obs::kEvConnDead, otag_conn, 0, 0);
      tpr_obs::metric_add(tpr_obs::kMetConnDown);
    }
    if (link) link->close();  // fail rdv waiters; quarantine leases
    CqDeliveries evs;
    {
      std::lock_guard<std::mutex> lk(mu);
      // Sweep + notify even when alive was already false: the *first*
      // flipper may have been ~tpr_channel, which doesn't sweep — an app
      // thread parked in a deadline-less tpr_call_recv/finish must still be
      // failed and woken, or it hangs on (then uses) a destroyed channel.
      alive.store(false);
      for (auto &kv : streams) {
        Call &c = kv.second->c;
        if (!c.trailers_seen) {
          c.trailers_seen = true;
          c.status_code = TPR_UNAVAILABLE;
          c.status_details = "connection lost";
        }
        drain_cq_locked(c, &evs);
      }
      cq_push(&evs);  // under mu: keeps cq ordering = generation ordering
    }
    cv.notify_all();
  }

  // Dispatch one frame. Returns 0 when the connection should end (last
  // in-flight call on a GOAWAY'd connection), else 1. Called with mu NOT
  // held (takes it itself), from the reader thread or an inline pumper.
  int process_frame(uint8_t type, uint8_t flags, uint32_t sid,
                    std::vector<uint8_t> &payload) {
    size_t len = payload.size();

    // Rendezvous / ctrl-ring control plane rides its own frame types —
    // routed before the stream demux (they address leases, not streams).
    if (type >= kRdvOffer && type <= kCtrlKick) {
      if (link) link->on_frame(type, sid, payload.data(), len);
      return 1;
    }
    if (type == kPing) {
      // hello negotiation piggybacks on PING (maybe_hello no-ops on
      // ordinary keepalive pings); always echo PONG regardless
      if (link) link->maybe_hello(payload.data(), len);
      send_frame(kPong, 0, 0, payload.data(), payload.size());
      return 1;
    }
    if (type == kPong) {
      {
        std::lock_guard<std::mutex> lk(mu);
        pong_count++;
      }
      cv.notify_all();
      return 1;
    }
    if (type == kGoaway) {
      // Graceful drain (server max_connection_age): stop admitting new
      // calls but keep reading so in-flight calls finish; the connection
      // dies when the last one completes (below) or at socket EOF.
      std::lock_guard<std::mutex> lk(mu);
      draining = true;
      return streams.empty() ? 0 : 1;
    }

    if (type == kMessage && (flags & kFlagCompressed)) {
      // Per-stream rejection, mirroring the native server's UNIMPLEMENTED
      // trailer: fail only the addressed stream (frames for unknown or
      // finished streams are simply ignored) instead of tearing down the
      // whole multiplexed connection and every unrelated in-flight call.
      // The details text must keep "compressed messages unsupported" as a
      // substring — the Python channel's compression negotiation keys on
      // it (tpurpc/rpc/frame.py COMPRESSED_UNSUPPORTED_SENTINEL). The
      // teardown sequence below intentionally mirrors the kTrailers/kRst
      // branch tail; keep the two in sync (cq ordering under mu, draining
      // rule).
      CqDeliveries cq_evs;
      std::unique_lock<std::mutex> lk(mu);
      auto it = streams.find(sid);
      if (it == streams.end()) return 1;  // late frame for a finished call
      Call &c = it->second->c;
      c.status_code = TPR_UNIMPLEMENTED;
      c.status_details =
          "compressed messages unsupported by the native client";
      c.trailers_seen = true;
      streams.erase(it);
      drain_cq_locked(c, &cq_evs);
      cq_push(&cq_evs);  // under mu: keeps cq ordering = generation ordering
      bool drained = draining && streams.empty();
      lk.unlock();
      cv.notify_all();
      // RST so the server stops streaming into the locally-dead stream.
      std::vector<std::pair<std::string, std::string>> rst_md;
      rst_md.emplace_back(":status", std::to_string(TPR_UNIMPLEMENTED));
      rst_md.emplace_back(":message", "compressed messages unsupported");
      std::string rst_payload = encode_metadata(rst_md);
      send_frame(kRst, 0, sid, rst_payload.data(), rst_payload.size());
      return drained ? 0 : 1;
    }
    // Framed bulk on a rendezvous-negotiated connection = a host landing
    // copy the rdv path would have avoided; the ledger keeps that honest.
    if (type == kMessage && link && link->negotiated.load())
      tpr_rdv::count(tpr_rdv::kCtrHostCopyBytes, len);
    CqDeliveries cq_evs;
    std::unique_lock<std::mutex> lk(mu);
    auto it = streams.find(sid);
    if (it == streams.end()) return 1;  // late frame for a finished call
    Call &c = it->second->c;
    if (type == kMessage) {
      if (!(flags & kFlagNoMessage))
        c.partial.append(reinterpret_cast<char *>(payload.data()), len);
      if (!(flags & kFlagMore) && !(flags & kFlagNoMessage)) {
        c.messages.push_back(std::move(c.partial));
        c.partial.clear();
      }
      if (flags & kFlagEndStream) {
        // server half-closed without trailers: tolerate, finish as OK
        c.trailers_seen = true;
        c.status_code = TPR_OK;
        streams.erase(it);
      }
    } else if (type == kHeaders) {
      // initial metadata: stored nowhere yet (API exposes trailers only)
    } else if (type == kTrailers || type == kRst) {
      std::vector<std::pair<std::string, std::string>> md;
      decode_metadata(payload.data(), len, &md);
      if (type == kRst && (flags & kFlagRefused)) c.refused = true;
      c.status_code = TPR_UNKNOWN;
      for (auto &kv : md) {
        if (kv.first == ":status") c.status_code = atoi(kv.second.c_str());
        else if (kv.first == ":message") c.status_details = kv.second;
      }
      c.trailers_seen = true;
      streams.erase(it);
    }
    drain_cq_locked(c, &cq_evs);
    cq_push(&cq_evs);  // under mu: keeps cq ordering = generation ordering
    bool drained = draining && streams.empty();
    lk.unlock();
    cv.notify_all();
    return drained ? 0 : 1;
  }

  // Bounded single-frame read for the hot ctrl-polling mode: 1 = frame,
  // 0 = nothing within ~1ms, -1 = transport dead. For TCP the 1ms bound is
  // on frame START (poll); once the header begins the read blocks to the
  // frame boundary — fine, the bytes are already in flight.
  int read_frame_slice(uint8_t *type, uint8_t *flags, uint32_t *sid,
                       std::vector<uint8_t> *payload) {
    if (ring) {
      auto dl = Clock::now() + std::chrono::milliseconds(1);
      return read_frame_dl(&dl, type, flags, sid, payload);
    }
    struct pollfd pfd;
    pfd.fd = fd;
    pfd.events = POLLIN;
    pfd.revents = 0;
    int pr = ::poll(&pfd, 1, 1);
    if (pr == 0) return 0;
    if (pr < 0) return errno == EINTR ? 0 : -1;
    return t_read_frame(*this, type, flags, sid, payload) ? 1 : -1;
  }

  void read_loop() {
    if (link) link->set_dispatch_thread();
    std::vector<uint8_t> payload;
    uint8_t type, flags;
    uint32_t sid;
    while (alive.load()) {
      int r;
      if (link && link->ctrl_rx_ready() && link->ctrl_hot()) {
        // hot discipline: poll the ctrl ring between 1ms frame slices —
        // steady-state bulk needs no frames and no fd kicks at all
        if (link->ctrl_drain() == 0) {
          link->ctrl_decay();
          if (!link->ctrl_hot()) link->ctrl_park();
        }
        r = read_frame_slice(&type, &flags, &sid, &payload);
        if (r == 0) continue;
      } else {
        // cold/parked: block on the fd; a parked producer sends CTRL_KICK
        r = t_read_frame(*this, &type, &flags, &sid, &payload) ? 1 : -1;
      }
      if (r < 0) break;
      // ctrl records whose ordering gate has been reached must land before
      // the frame they precede (Python pre-commit drain analog)
      if (link) link->ctrl_drain();
      int cont = process_frame(type, flags, sid, payload);
      if (link) {
        link->frames_dispatched.fetch_add(1, std::memory_order_release);
        // re-drain AFTER the dispatch count advances: a record gated on
        // exactly this frame deferred in the pre-dispatch drain, and the
        // producer may have posted it while we were unparked — without
        // this pass it would strand until the next (possibly never)
        // frame arrives (observed as 5s claim timeouts)
        link->ctrl_drain();
      }
      if (cont == 0) break;
    }
    die();
  }

  // One frame read whose HEADER wait is bounded by `dl` (frame-boundary
  // abandon; ring only). 1 = frame delivered, 0 = deadline, -1 = dead.
  int read_frame_dl(const Clock::time_point *dl, uint8_t *type,
                    uint8_t *flags, uint32_t *sid,
                    std::vector<uint8_t> *payload) {
    uint8_t hdr[10];
    int r = ring->read_exact_deadline(hdr, sizeof hdr, dl);
    if (r <= 0) return r;
    return t_finish_frame(*ring, hdr, type, flags, sid, payload) ? 1 : -1;
  }

  // Inline-read discipline (TPURPC_NATIVE_INLINE_READ=1, ring platforms):
  // the WAITING thread pumps the transport itself — the reference's
  // pollset_work model (grpc_completion_queue_next → pollable_epoll,
  // SURVEY §3.4) — eliminating the reader→caller thread wakeup from every
  // round trip. One pumper at a time; others park on cv and inherit the
  // pump when it is released. Returns false only when `dl` passed without
  // pred becoming true.
  template <typename Pred>
  bool pump_until(std::unique_lock<std::mutex> &lk, Pred pred,
                  const Clock::time_point *dl) {
    std::vector<uint8_t> payload;
    uint8_t type, flags;
    uint32_t sid;
    while (!pred()) {
      if (!alive.load()) return true;  // terminal state; caller decodes it
      // Own-deadline check BEFORE (re)taking the pump: a pumper servicing
      // another stream's continuous traffic never hits the header-wait
      // timeout inside read_exact_deadline, so without this check its
      // deadline could be starved for as long as frames keep arriving.
      if (dl != nullptr && Clock::now() >= *dl) return false;
      if (pumping) {
        // another thread is inside the transport; wait for its dispatch
        if (dl != nullptr) {
          if (cv.wait_until(lk, *dl) == std::cv_status::timeout && !pred())
            return false;
        } else {
          cv.wait(lk);
        }
        continue;
      }
      pumping = true;
      lk.unlock();
      if (link) link->ctrl_drain();  // inline pumpers service the ring too
      int r;
      if (link && link->ctrl_rx_ready() && link->ctrl_hot()) {
        // read_loop's hot/cold ctrl discipline, inline-pumper edition: a
        // pumper must never commit to a blocking read while the ctrl ring
        // is unparked — a producer that read parked=0 skips the CTRL_KICK,
        // so a record posted behind this read would strand until some
        // unrelated frame arrives (the defer-then-block lost wakeup,
        // observed as 5s claim timeouts). Poll in 1ms slices while hot;
        // park before blocking for real.
        auto slice = Clock::now() + std::chrono::milliseconds(1);
        const Clock::time_point *sdl =
            (dl != nullptr && *dl < slice) ? dl : &slice;
        r = read_frame_dl(sdl, &type, &flags, &sid, &payload);
        if (r == 0 && link->ctrl_drain() == 0) {
          link->ctrl_decay();
          if (!link->ctrl_hot()) link->ctrl_park();
        }
      } else {
        r = read_frame_dl(dl, &type, &flags, &sid, &payload);
      }
      int cont = 1;
      if (r == 1) {
        if (link) link->ctrl_drain();
        cont = process_frame(type, flags, sid, payload);
        if (link) {
          link->frames_dispatched.fetch_add(1, std::memory_order_release);
          link->ctrl_drain();  // lift the gate for records on THIS frame
        }
      }
      lk.lock();
      pumping = false;
      cv.notify_all();  // deliver wakeups + hand off the pump
      if (r < 0 || cont == 0) {
        lk.unlock();
        die();
        lk.lock();
      }
      // r == 0: a slice or deadline expired at a frame boundary — loop;
      // the own-deadline check at the top returns false when `dl` truly
      // passed (slice expiries with dl unset just keep pumping).
    }
    return true;
  }
};

// ---------------------------------------------------------------------------

// RST the stream and record a local terminal status. Servers do NOT
// acknowledge an RST with trailers (tpurpc/rpc/server.py cancels the
// context and goes quiet), so the call must finish locally — otherwise a
// deadline-less Finish() after Cancel() would wait forever. A real trailers
// frame that raced in first wins.
static void rst_and_finish_locally(tpr_call *c, int code,
                                   const char *details) {
  tpr_channel *ch = c->c.ch;
  uint32_t sid;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    if (c->c.cancelled || c->c.trailers_seen) return;
    c->c.cancelled = true;
    c->c.internal_users++;  // pins `c` across the unlocked send below —
    sid = c->c.stream_id;   // tpr_call_destroy waits for users to drain
  }
  std::vector<std::pair<std::string, std::string>> md;
  md.emplace_back(":status", std::to_string(TPR_CANCELLED));
  md.emplace_back(":message", details);
  std::string payload = encode_metadata(md);
  ch->send_frame(kRst, 0, sid, payload.data(), payload.size());
  CqDeliveries evs;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    ch->streams.erase(sid);
    if (!c->c.trailers_seen) {
      c->c.trailers_seen = true;
      c->c.status_code = code;
      c->c.status_details = details;
    }
    drain_cq_locked(c->c, &evs);
    cq_push(&evs);  // under mu: keeps cq ordering = generation ordering
    c->c.internal_users--;
  }
  ch->cv.notify_all();
}

// CQ deadline expiry: terminate + DELIVER FIRST, then best-effort RST.
// rst_and_finish_locally won't do here — its cancelled/trailers_seen guard
// early-returns when a concurrent tpr_call_cancel won the race, and that
// canceller can sit wedged in its RST send indefinitely (peer stopped
// reading), which would strand the armed finish/unary tag forever. The
// blocking API bounds the same race with a 5 s wait (tpr_call_finish);
// the CQ path must not lose the completion at all. Setting the terminal
// status before the RST is safe: a real trailers frame racing in later
// finds trailers_seen set and drain emits nothing twice. The trailing RST
// send can block only if the socket buffer is full of the app's own
// wedged bulk writes — the same bounded exposure the blocking cancel has.
static void cq_expire(tpr_call *c, int code, const char *details) {
  tpr_channel *ch = c->c.ch;
  uint32_t sid = 0;
  bool send_rst = false;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    if (!c->c.trailers_seen) {
      c->c.trailers_seen = true;
      c->c.status_code = code;
      c->c.status_details = details;
      send_rst = !c->c.cancelled;  // a racing cancel already ships an RST
      c->c.cancelled = true;       // make later cancels no-ops
      sid = c->c.stream_id;
      ch->streams.erase(sid);
      CqDeliveries evs;
      drain_cq_locked(c->c, &evs);
      cq_push(&evs);
    }
  }
  ch->cv.notify_all();
  if (send_rst) {
    std::vector<std::pair<std::string, std::string>> md;
    md.emplace_back(":status", std::to_string(TPR_CANCELLED));
    md.emplace_back(":message", details);
    std::string payload = encode_metadata(md);
    ch->send_frame(kRst, 0, sid, payload.data(), payload.size());
  }
}


extern "C" {

tpr_channel *tpr_channel_create(const char *host, int port, int timeout_ms) {
  // env-derived default discipline (TPURPC_NATIVE_INLINE_READ)
  const char *inl = getenv("TPURPC_NATIVE_INLINE_READ");
  return tpr_channel_create2(host, port, timeout_ms,
                             (inl != nullptr && inl[0] == '1')
                                 ? TPR_CHANNEL_INLINE_READ
                                 : 0);
}

tpr_channel *tpr_channel_create2(const char *host, int port, int timeout_ms,
                                 int flags) {
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof portbuf, "%d", port);
  struct addrinfo *res = nullptr;
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || res == nullptr)
    return nullptr;
  int fd = -1;
  for (struct addrinfo *ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype | SOCK_NONBLOCK,
                  ai->ai_protocol);
    if (fd < 0) continue;
    int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      struct pollfd pfd {fd, POLLOUT, 0};
      if (::poll(&pfd, 1, timeout_ms > 0 ? timeout_ms : -1) == 1) {
        int err = 0;
        socklen_t len = sizeof err;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        rc = err == 0 ? 0 : -1;
      } else {
        rc = -1;  // timeout
      }
    }
    if (rc == 0) {
      int fl = fcntl(fd, F_GETFL);
      fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);  // back to blocking for IO
      break;
    }
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);

  auto *ch = new tpr_channel();
  ch->fd = fd;
  if (platform_wants_ring()) {
    // the reference's defining property: app code unchanged, the byte pipe
    // under it swapped by env (endpoint.cc:33-54) — now for native apps too
    auto *rt = new tpr_ring::RingTransport();
    std::string err;
    if (!rt->bootstrap(fd, ring_size_from_env(), /*preread_magic=*/false,
                       &err, timeout_ms)) {
      fprintf(stderr, "tpurpc: ring bootstrap failed: %s\n", err.c_str());
      rt->close();
      delete rt;
      delete ch;
      return nullptr;
    }
    ch->ring = rt;
  }
  if (!ch->write_all(kMagic, 8)) {
    delete ch;
    return nullptr;
  }
  if (tpr_rdv::enabled()) {
    // Rendezvous link: send_frame goes through ch->send_frame (which also
    // does the frames_sent accounting); deliver copies the landing region
    // into the call mailbox then settles the lease. The client API's recv
    // copies out of a std::string anyway, so the zero-landing-copy win is
    // a server-side property; the client-side rdv win is skipping framed
    // fragmentation + per-frame wakeups on send.
    ch->link = new tpr_rdv::Link("cli");
    ch->link->send_frame = [ch](uint8_t type, uint32_t sid,
                                const std::string &payload) {
      return ch->send_frame(type, 0, sid, payload.data(), payload.size());
    };
    ch->link->deliver = [ch](uint32_t sid, uint8_t dflags, uint8_t *data,
                             size_t len) {
      CqDeliveries evs;
      {
        std::unique_lock<std::mutex> lk(ch->mu);
        auto it = ch->streams.find(sid);
        if (it != ch->streams.end()) {
          Call &c = it->second->c;
          c.messages.emplace_back(reinterpret_cast<char *>(data), len);
          if (dflags & kFlagEndStream) {
            c.trailers_seen = true;
            c.status_code = TPR_OK;
            ch->streams.erase(it);
          }
          drain_cq_locked(c, &evs);
          cq_push(&evs);
        }
      }
      ch->cv.notify_all();
      tpr_rdv::settle(data);  // recycle the lease; `data` is region memory
    };
    ch->link->wake = [ch] { ch->cv.notify_all(); };
    if (!ch->send_frame(kPing, 0, 0, ch->link->hello_payload().data(),
                        ch->link->hello_payload().size())) {
      delete ch;
      return nullptr;
    }
  }
  // Inline-read (opt-in, ring platforms): the lowest-latency blocking
  // discipline — callers pump the transport themselves, no reader thread.
  // CQ async ops need the reader and refuse on such channels.
  ch->inline_read =
      ch->ring != nullptr && (flags & TPR_CHANNEL_INLINE_READ) != 0;
  if (ch->inline_read && ch->link) {
    // no reader thread: rdv claim waiters pump the transport themselves
    ch->link->pump = [ch](const std::function<bool()> &pred,
                          Clock::time_point dl) {
      std::unique_lock<std::mutex> lk(ch->mu);
      ch->pump_until(lk, [&] { return pred(); }, &dl);
    };
  }
  if (!ch->inline_read)
    ch->reader = std::thread([ch] { ch->read_loop(); });
  if (tpr_obs::enabled()) {
    static std::atomic<uint64_t> conn_ord{1};
    char tb[44];
    snprintf(tb, sizeof tb, "nconn:cli#%llu",
             (unsigned long long)conn_ord.fetch_add(
                 1, std::memory_order_relaxed));
    ch->otag_conn = tpr_obs::tag_for(tb);
    TPR_OBS(tpr_obs::kEvConnConnect, ch->otag_conn, 0, 0);
    tpr_obs::metric_add(tpr_obs::kMetConnUp);
  }
  return ch;
}

static void abort_lease_if_owned(tpr_channel *ch);  // defined with the lease API

void tpr_channel_destroy(tpr_channel *ch) {
  // Last-resort abandoned-lease recovery before ~tpr_channel joins the
  // reader (which a wedged write_mu could deadlock behind a sender).
  abort_lease_if_owned(ch);
  delete ch;
}

int64_t tpr_channel_ping(tpr_channel *ch, int timeout_ms) {
  uint64_t before;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    before = ch->pong_count;
  }
  auto t0 = Clock::now();
  if (!ch->send_frame(kPing, 0, 0, "p", 1)) return -1;
  std::unique_lock<std::mutex> lk(ch->mu);
  auto pred = [&] { return ch->pong_count > before || !ch->alive.load(); };
  bool ok;
  if (ch->inline_read) {
    auto dl = t0 + std::chrono::milliseconds(timeout_ms);
    ok = ch->pump_until(lk, pred, &dl);
  } else {
    ok = ch->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
  }
  if (!ok || ch->pong_count <= before) return -1;
  return std::chrono::duration_cast<std::chrono::microseconds>(Clock::now() - t0)
      .count();
}

// Internal: register a stream + build its HEADERS payload (shared by the
// normal and buffered start paths — one copy of the draining gate,
// stream-id allocation, deadline setup, and :path/:timeout-us metadata).
static tpr_call *register_call(tpr_channel *ch, const char *method,
                               const char *const *metadata, size_t n_md,
                               int timeout_ms, std::string *hdr_payload) {
  if (!ch->alive.load()) return nullptr;
  auto *call = new tpr_call();
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    if (ch->draining) {  // GOAWAY'd: the app must open a fresh channel
      delete call;
      return nullptr;
    }
    call->c.stream_id = ch->next_stream_id;
    ch->next_stream_id += 2;
    call->c.ch = ch;
    if (timeout_ms > 0) {
      call->c.has_deadline = true;
      call->c.deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    }
    ch->streams[call->c.stream_id] = call;
  }
  std::vector<std::pair<std::string, std::string>> md;
  md.emplace_back(":path", method);
  if (timeout_ms > 0)
    md.emplace_back(":timeout-us", std::to_string(int64_t(timeout_ms) * 1000));
  for (size_t i = 0; i + 1 < 2 * n_md; i += 2)
    md.emplace_back(metadata[i], metadata[i + 1]);
  *hdr_payload = encode_metadata(md);
  return call;
}

static void unregister_call(tpr_channel *ch, tpr_call *call) {
  std::lock_guard<std::mutex> lk(ch->mu);
  ch->streams.erase(call->c.stream_id);
  delete call;
}

// Internal: ship HEADERS + the whole request MESSAGE (END_STREAM) for a
// registered call as one buffered write (one syscall / ring message).
static bool ship_buffered(tpr_channel *ch, tpr_call *call,
                          const std::string &hdr_payload, const uint8_t *req,
                          size_t req_len) {
  std::string buf;
  buf.reserve(20 + hdr_payload.size() + req_len);
  build_frame_header(buf, kHeaders, 0, call->c.stream_id,
                     hdr_payload.size());
  buf += hdr_payload;
  build_frame_header(buf, kMessage, kFlagEndStream, call->c.stream_id,
                     req_len);
  buf.append(reinterpret_cast<const char *>(req), req_len);
  std::lock_guard<std::mutex> lk(ch->write_mu);
  bool ok =
      ch->alive.load() &&
      (ch->ring ? ch->ring->write_gather(buf.data(), buf.size(), nullptr, 0)
                : tpr_wire::fd_write_all(ch->fd, buf.data(), buf.size()));
  // this path bypasses send_frame; it ships TWO frames in one write
  if (ok && ch->link)
    ch->link->frames_sent.fetch_add(2, std::memory_order_release);
  return ok;
}

// Internal: register a call and ship HEADERS + the whole request MESSAGE
// (END_STREAM) as one buffered write. Small-unary fast path only.
static tpr_call *tpr_call_start_buffered(tpr_channel *ch, const char *method,
                                         int timeout_ms, const uint8_t *req,
                                         size_t req_len) {
  std::string hdr_payload;
  tpr_call *call = register_call(ch, method, nullptr, 0, timeout_ms,
                                 &hdr_payload);
  if (!call) return nullptr;
  if (!ship_buffered(ch, call, hdr_payload, req, req_len)) {
    unregister_call(ch, call);
    return nullptr;
  }
  return call;
}

tpr_call *tpr_call_start(tpr_channel *ch, const char *method,
                         const char *const *metadata, size_t n_md,
                         int timeout_ms) {
  std::string payload;
  tpr_call *call = register_call(ch, method, metadata, n_md, timeout_ms,
                                 &payload);
  if (!call) return nullptr;
  if (!ch->send_frame(kHeaders, 0, call->c.stream_id, payload.data(),
                      payload.size())) {
    unregister_call(ch, call);
    return nullptr;
  }
  return call;
}

int tpr_call_send(tpr_call *c, const uint8_t *data, size_t len, int end_stream) {
  tpr_channel *ch = c->c.ch;
  // Rendezvous ladder first: on a negotiated native connection, payloads
  // at/above the threshold one-sided-write into a leased landing region —
  // no framed fragmentation, and in steady state no control frames either
  // (the COMPLETE rides the ctrl ring). Any failure falls through framed.
  if (ch->link && ch->link->eligible(len) &&
      ch->link->send_message(c->c.stream_id,
                             end_stream ? kFlagEndStream : 0, data, len))
    return 0;
  // fragment at the frame bound with MORE on all but the last piece
  size_t off = 0;
  do {
    size_t n = len - off;
    bool last = n <= kMaxFramePayload;
    if (!last) n = kMaxFramePayload;
    uint8_t flags = 0;
    if (!last) flags |= kFlagMore;
    if (last && end_stream) flags |= kFlagEndStream;
    if (!ch->send_frame(kMessage, flags, c->c.stream_id, data + off, n))
      return -1;
    off += n;
  } while (off < len);
  return 0;
}

static int send_reserve_flagged(tpr_call *c, size_t len, uint8_t fflags,
                                uint8_t **p1, size_t *l1,
                                uint8_t **p2, size_t *l2) {
  // Zero-copy send (the reference's SendZerocopy shape, pair.cc:793-941,
  // recast for a shm ring): reserve ONE message's span in the peer ring so
  // the producer SERIALIZES INTO THE TRANSPORT — the staging buffer and
  // its memcpy disappear. The 10-byte frame header is written here; the
  // caller fills the returned payload segments then commits. write_mu is
  // HELD between reserve and commit/abort: commit promptly from the same
  // thread, and issue no other sends in between (they would deadlock).
  tpr_channel *ch = c->c.ch;
  if (ch->ring == nullptr || len == 0 || len > kMaxFramePayload) return -1;
  // BEFORE taking write_mu: the holder of an uncommitted lease already
  // owns the lock, so a same-thread re-reserve must fail fast here — the
  // lock() below would self-deadlock a non-recursive mutex (and another
  // thread's reserve would block, which is just normal send serialization)
  if (ch->lease_active.load()) return -1;
  ch->write_mu.lock();
  if (!ch->alive.load() || ch->lease_active.load()) {
    ch->write_mu.unlock();
    return -1;
  }
  uint64_t total = 10 + (uint64_t)len;
  uint8_t *q1;
  uint64_t m1;
  uint8_t *q2;
  uint64_t m2;
  if (!ch->ring->reserve_lease(total, &q1, &m1, &q2, &m2)) {
    ch->write_mu.unlock();
    return -1;
  }
  std::string hdr;
  build_frame_header(hdr, kMessage, fflags, c->c.stream_id, len);
  // header may straddle the wrap split
  size_t h1 = hdr.size() < m1 ? hdr.size() : (size_t)m1;
  memcpy(q1, hdr.data(), h1);
  if (h1 < hdr.size()) memcpy(q2, hdr.data() + h1, hdr.size() - h1);
  if (m1 > hdr.size()) {
    *p1 = q1 + hdr.size();
    *l1 = (size_t)(m1 - hdr.size());
    *p2 = q2;
    *l2 = (size_t)m2;
  } else {
    *p1 = q2 + (hdr.size() - m1);
    *l1 = (size_t)(m2 - (hdr.size() - m1));
    *p2 = nullptr;
    *l2 = 0;
  }
  ch->lease_owner = std::this_thread::get_id();
  ch->lease_len = total;
  ch->lease_active.store(true);
  return 0;
}

int tpr_call_send_reserve(tpr_call *c, size_t len, int end_stream,
                          uint8_t **p1, size_t *l1,
                          uint8_t **p2, size_t *l2) {
  return send_reserve_flagged(c, len, end_stream ? kFlagEndStream : 0,
                              p1, l1, p2, l2);
}

int tpr_call_send_reserve2(tpr_call *c, size_t len, int flags,
                           uint8_t **p1, size_t *l1,
                           uint8_t **p2, size_t *l2) {
  // Fragment-aware lease: TPR_RESERVE_MORE marks this frame as a non-final
  // fragment of one message (kFlagMore), so a producer can gather a payload
  // LARGER than kMaxFramePayload through several leases and the peer still
  // reassembles ONE message — the zero-copy analog of tpr_call_send's
  // fragmentation loop. TPR_RESERVE_END_STREAM only makes sense on the
  // final fragment (callers pass it with MORE clear).
  uint8_t f = 0;
  if (flags & TPR_RESERVE_END_STREAM) f |= kFlagEndStream;
  if (flags & TPR_RESERVE_MORE) f |= kFlagMore;
  return send_reserve_flagged(c, len, f, p1, l1, p2, l2);
}

// Only the RESERVING thread may finish a lease: a stranger "committing"
// would publish a half-filled message to the peer and unlock a mutex it
// never locked (both UB). The owner-id gate turns that misuse into -1.
static bool lease_owned_by_me(tpr_channel *ch) {
  return ch->lease_active.load() &&
         ch->lease_owner == std::this_thread::get_id();
}

// Abandoned-lease recovery (ADVICE r5): a caller that throws between
// reserve and commit/abort (ctypes exception mid-fill) would otherwise
// leave write_mu locked forever, wedging every send on the channel. The
// destroy paths call this so same-thread cleanup (the normal Python
// exception unwind: reserve → raise → call/channel destroy) releases the
// lease. Reserve never advanced the tail, so the span is simply reused.
// Only the owning thread can recover — unlocking a foreign thread's mutex
// is UB — which matches the failure mode: the thread that abandoned the
// lease is the one running the unwind.
static void abort_lease_if_owned(tpr_channel *ch) {
  if (lease_owned_by_me(ch)) {
    ch->lease_active.store(false);
    ch->write_mu.unlock();
  }
}

int tpr_call_send_commit(tpr_call *c) {
  tpr_channel *ch = c->c.ch;
  if (!lease_owned_by_me(ch)) return -1;
  ch->ring->commit_lease(ch->lease_len);
  // the lease published one MESSAGE frame outside send_frame
  if (ch->link)
    ch->link->frames_sent.fetch_add(1, std::memory_order_release);
  ch->lease_active.store(false);
  ch->write_mu.unlock();
  return 0;
}

int tpr_call_send_abort(tpr_call *c) {
  // Un-publish: reserve never advanced the tail, so the span is simply
  // reused by the next send. Releases the channel's send path.
  tpr_channel *ch = c->c.ch;
  if (!lease_owned_by_me(ch)) return -1;
  ch->lease_active.store(false);
  ch->write_mu.unlock();
  return 0;
}

int tpr_call_writes_done(tpr_call *c) {
  return c->c.ch->send_frame(kMessage, kFlagEndStream | kFlagNoMessage,
                             c->c.stream_id, nullptr, 0)
             ? 0
             : -1;
}

static bool wait_event(tpr_call *c, std::unique_lock<std::mutex> &lk) {
  tpr_channel *ch = c->c.ch;
  auto ready = [&] {
    return !c->c.messages.empty() || c->c.trailers_seen || !ch->alive.load();
  };
  if (ch->inline_read)
    return ch->pump_until(lk, ready,
                          c->c.has_deadline ? &c->c.deadline : nullptr);
  if (c->c.has_deadline)
    return ch->cv.wait_until(lk, c->c.deadline, ready);
  ch->cv.wait(lk, ready);
  return true;
}

int tpr_call_recv(tpr_call *c, uint8_t **data, size_t *len) {
  tpr_channel *ch = c->c.ch;
  std::unique_lock<std::mutex> lk(ch->mu);
  while (true) {
    if (!c->c.messages.empty()) {
      std::string &m = c->c.messages.front();
      *len = m.size();
      *data = static_cast<uint8_t *>(malloc(m.size() ? m.size() : 1));
      memcpy(*data, m.data(), m.size());
      c->c.messages.pop_front();
      return 1;
    }
    if (c->c.trailers_seen) return 0;
    if (!ch->alive.load()) return -1;
    if (!wait_event(c, lk)) return -1;  // deadline
  }
}

int tpr_call_finish(tpr_call *c, char *details, size_t cap) {
  tpr_channel *ch = c->c.ch;
  std::unique_lock<std::mutex> lk(ch->mu);
  while (!c->c.trailers_seen) {
    if (!ch->alive.load()) {
      c->c.trailers_seen = true;
      c->c.status_code = TPR_UNAVAILABLE;
      c->c.status_details = "connection lost";
      break;
    }
    if (!wait_event(c, lk)) {  // client-side deadline
      lk.unlock();
      rst_and_finish_locally(c, TPR_DEADLINE_EXCEEDED,
                             "deadline exceeded (client)");
      lk.lock();
      // If a concurrent cancel won the race, rst_and_finish_locally
      // early-returned without a terminal status; the racing thread sets
      // trailers_seen right after its RST send completes — wait for it,
      // BOUNDED: that thread can itself be stuck in send() on a peer that
      // stopped reading, and a deadline-exceeded call must never hang.
      ch->cv.wait_for(lk, std::chrono::seconds(5), [&] {
        return c->c.trailers_seen || !ch->alive.load();
      });
      if (!c->c.trailers_seen) {
        c->c.trailers_seen = true;
        c->c.status_code = TPR_DEADLINE_EXCEEDED;
        c->c.status_details = "deadline exceeded (client)";
      }
      break;
    }
  }
  if (details && cap > 0) {
    size_t n = c->c.status_details.size();
    if (n >= cap) n = cap - 1;
    memcpy(details, c->c.status_details.data(), n);
    details[n] = '\0';
  }
  return c->c.status_code;
}

void tpr_call_cancel(tpr_call *c) {
  rst_and_finish_locally(c, TPR_CANCELLED, "cancelled by client");
}

void tpr_call_destroy(tpr_call *c) {
  tpr_channel *ch = c->c.ch;
  // An exception between send_reserve and commit unwinds through here:
  // free the channel's send path before anything that could block on it.
  abort_lease_if_owned(ch);
  if (c->c.cq != nullptr) {
    // Unhook from the queue's deadline scan first: a tpr_cq_next thread may
    // be mid-expiry holding `c` (cq_pins) — wait for it, bounded, with the
    // same leak-over-UAF policy as internal_users below.
    tpr_cq *cq = c->c.cq;
    std::unique_lock<std::mutex> lk(cq->mu);
    cq->timed_calls.erase(c);
    cq->cv.wait_for(lk, std::chrono::seconds(30),
                    [&] { return c->c.cq_pins == 0; });
    if (c->c.cq_pins != 0) return;  // pathological: leak beats corruption
  }
  {
    std::unique_lock<std::mutex> lk(ch->mu);
    ch->streams.erase(c->c.stream_id);
    // A cancel/deadline thread may still be inside its (possibly stuck)
    // RST send holding `c`; freeing now would be a use-after-free when it
    // resumes. Wait for it to drain — bounded: if the send is wedged past
    // any reasonable socket stall, deliberately leak the call object (a
    // leak on a pathological connection beats heap corruption).
    bool drained = ch->cv.wait_for(lk, std::chrono::seconds(30), [&] {
      return c->c.internal_users == 0;
    });
    if (!drained) return;  // leak: racer still holds `c`
  }
  delete c;
}

void tpr_buf_free(uint8_t *data) { free(data); }

int tpr_unary_call_ex(tpr_channel *ch, const char *method, const uint8_t *req,
                      size_t req_len, uint8_t **resp, size_t *resp_len,
                      char *details, size_t details_cap, int timeout_ms,
                      int *preexec) {
  // *preexec==1 marks the three early returns below — the ONLY failures
  // where the complete request provably never left this process (admission
  // refusal, or fd_write_all/ring write returning false, which leaves at
  // least the trailing END_STREAM byte unsent so no unary handler can have
  // run). Everything past the send is 0: a handler may have executed.
  if (preexec) *preexec = 0;
  tpr_call *c;
  if (req_len <= kSmallUnaryMax) {
    // small-unary fast path: HEADERS + MESSAGE|END_STREAM leave in ONE
    // write (one syscall / one ring message+notify). Two separate writes
    // cost a second wakeup on both sides — measured as the native unary
    // path LOSING to the Python client (which batches) on loopback.
    c = tpr_call_start_buffered(ch, method, timeout_ms, req, req_len);
    if (!c) {
      if (details && details_cap)
        snprintf(details, details_cap, "channel dead or send failed");
      if (preexec) *preexec = 1;
      return TPR_UNAVAILABLE;
    }
  } else {
    c = tpr_call_start(ch, method, nullptr, 0, timeout_ms);
    if (!c) {
      if (details && details_cap)
        snprintf(details, details_cap, "channel dead");
      if (preexec) *preexec = 1;
      return TPR_UNAVAILABLE;
    }
    if (tpr_call_send(c, req, req_len, /*end_stream=*/1) != 0) {
      tpr_call_destroy(c);
      if (details && details_cap)
        snprintf(details, details_cap, "send failed");
      if (preexec) *preexec = 1;
      return TPR_UNAVAILABLE;
    }
  }
  uint8_t *data = nullptr;
  size_t len = 0;
  int got = tpr_call_recv(c, &data, &len);
  int code = tpr_call_finish(c, details, details_cap);
  // Admission refusal (kRst|kFlagRefused, e.g. a max_age GOAWAY race): the
  // SERVER certifies no handler ran, so the failure is replay-safe even
  // though the request left this process. finish() returned, so the RST was
  // fully processed before this read (no torn state).
  if (preexec && code != TPR_OK && c->c.refused) *preexec = 1;
  if (code == TPR_OK && got == 1) {
    *resp = data;
    *resp_len = len;
  } else if (got == 1) {
    tpr_buf_free(data);
  } else if (code == TPR_OK) {
    code = TPR_INTERNAL;  // OK status but no response message
    if (details && details_cap) snprintf(details, details_cap, "no response");
  }
  tpr_call_destroy(c);
  return code;
}

int tpr_unary_call(tpr_channel *ch, const char *method, const uint8_t *req,
                   size_t req_len, uint8_t **resp, size_t *resp_len,
                   char *details, size_t details_cap, int timeout_ms) {
  return tpr_unary_call_ex(ch, method, req, req_len, resp, resp_len, details,
                           details_cap, timeout_ms, nullptr);
}

/* -- completion-queue async API ------------------------------------------- */

tpr_cq *tpr_cq_create(void) { return new tpr_cq(); }

void tpr_cq_shutdown(tpr_cq *cq) {
  {
    std::lock_guard<std::mutex> lk(cq->mu);
    cq->shut = true;
  }
  cq->cv.notify_all();
}

void tpr_cq_destroy(tpr_cq *cq) { delete cq; }

static void fill_event(tpr_event *ev, CqEvent &e) {
  ev->type = e.type;
  ev->tag = e.tag;
  ev->ok = e.ok;
  ev->data = nullptr;
  ev->len = 0;
  if (e.has_data) {
    ev->len = e.data.size();
    ev->data = static_cast<uint8_t *>(malloc(e.data.size() ? e.data.size() : 1));
    memcpy(ev->data, e.data.data(), e.data.size());
  }
  ev->status = e.status;
  size_t n = e.details.size();
  if (n >= sizeof ev->details) n = sizeof ev->details - 1;
  memcpy(ev->details, e.details.data(), n);
  ev->details[n] = '\0';
}

int tpr_cq_next(tpr_cq *cq, tpr_event *ev, int timeout_ms) {
  const bool bounded = timeout_ms > 0;
  const auto overall = Clock::now() + std::chrono::milliseconds(
                                          bounded ? timeout_ms : 0);
  std::unique_lock<std::mutex> lk(cq->mu);
  while (true) {
    // Deadline enforcement FIRST, even with events queued: on a busy queue
    // the early return would otherwise starve expiries indefinitely — the
    // puller is the timer thread, so expiry latency must be bounded by one
    // cq_next call, not by traffic quiescence.
    tpr_call *expired = nullptr;
    auto earliest = Clock::time_point::max();
    const auto now = Clock::now();
    for (auto it = cq->timed_calls.begin(); it != cq->timed_calls.end();) {
      tpr_call *tc = *it;
      if (tc->c.done.load()) {  // finished normally; drop from the scan
        it = cq->timed_calls.erase(it);
        continue;
      }
      if (tc->c.deadline <= now) {
        expired = tc;
        break;
      }
      if (tc->c.deadline < earliest) earliest = tc->c.deadline;
      ++it;
    }
    if (expired != nullptr) {
      expired->c.cq_pins++;  // pins `expired` across the unlocked expiry
      lk.unlock();
      cq_expire(expired, TPR_DEADLINE_EXCEEDED, "deadline exceeded (client)");
      lk.lock();
      expired->c.cq_pins--;
      cq->timed_calls.erase(expired);
      cq->cv.notify_all();  // a destroy may be waiting for the pin drain
      continue;             // cq_expire queued this call's completions
    }
    if (!cq->q.empty()) {
      fill_event(ev, cq->q.front());
      cq->q.pop_front();
      return 1;
    }
    if (cq->shut) {
      memset(ev, 0, sizeof *ev);
      ev->type = TPR_EV_SHUTDOWN;
      return -1;
    }
    if (bounded && Clock::now() >= overall) return 0;
    auto wake = earliest;
    if (bounded && overall < wake) wake = overall;
    if (wake == Clock::time_point::max())
      cq->cv.wait(lk);
    else
      cq->cv.wait_until(lk, wake);
  }
}

tpr_call *tpr_call_start_cq(tpr_channel *ch, const char *method,
                            const char *const *metadata, size_t n_md,
                            int timeout_ms, tpr_cq *cq) {
  if (ch->inline_read) return nullptr;  // CQ needs the reader thread
  {
    std::lock_guard<std::mutex> lk(cq->mu);
    if (cq->shut) return nullptr;
  }
  std::string payload;
  tpr_call *call = register_call(ch, method, metadata, n_md, timeout_ms,
                                 &payload);
  if (call == nullptr) return nullptr;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    call->c.cq = cq;  // before HEADERS leave: the reader reads it under mu
  }
  if (!ch->send_frame(kHeaders, 0, call->c.stream_id, payload.data(),
                      payload.size())) {
    unregister_call(ch, call);
    return nullptr;
  }
  if (call->c.has_deadline) {
    // Notify: an already-parked tpr_cq_next must recompute its wake time
    // around the new deadline or it sleeps through the expiry.
    std::lock_guard<std::mutex> lk(cq->mu);
    cq->timed_calls.insert(call);
    cq->cv.notify_all();
  }
  return call;
}

// A shut queue refuses new ops (client.h contract): once tpr_cq_next has
// returned -1 the app may destroy the queue, so accepting a late op would
// let a future delivery write into freed memory.
static bool cq_refused(tpr_cq *cq) {
  std::lock_guard<std::mutex> lk(cq->mu);
  return cq->shut;
}

int tpr_call_recv_cq(tpr_call *c, void *tag) {
  if (c->c.cq == nullptr || cq_refused(c->c.cq)) return -1;
  tpr_channel *ch = c->c.ch;
  CqDeliveries evs;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    c->c.recv_tags.push_back(tag);
    drain_cq_locked(c->c, &evs);  // may complete immediately
    cq_push(&evs);
  }
  return 0;
}

int tpr_call_finish_cq(tpr_call *c, void *tag) {
  if (c->c.cq == nullptr || cq_refused(c->c.cq)) return -1;
  tpr_channel *ch = c->c.ch;
  CqDeliveries evs;
  {
    std::lock_guard<std::mutex> lk(ch->mu);
    if (c->c.finish_armed) return -1;  // at most one finish op per call
    c->c.finish_armed = true;
    c->c.finish_tag = tag;
    drain_cq_locked(c->c, &evs);
    cq_push(&evs);
  }
  return 0;
}

tpr_call *tpr_unary_call_cq(tpr_channel *ch, const char *method,
                            const uint8_t *req, size_t req_len,
                            int timeout_ms, tpr_cq *cq, void *tag) {
  if (ch->inline_read) return nullptr;  // CQ needs the reader thread
  {
    std::lock_guard<std::mutex> lk(cq->mu);
    if (cq->shut) return nullptr;
  }
  std::string hdr_payload;
  tpr_call *call = register_call(ch, method, nullptr, 0, timeout_ms,
                                 &hdr_payload);
  if (call == nullptr) return nullptr;
  bool timed = call->c.has_deadline;
  {
    // Arm BEFORE the request leaves: the response may race back and be
    // delivered by the reader in the gap after the send returns. Also pin
    // the call (internal_users) — once the completion is deliverable, a
    // puller thread may legally tpr_call_destroy it before this thread
    // runs again, and destroy must wait for us (it already waits for the
    // cancel path's pin on the same counter).
    std::lock_guard<std::mutex> lk(ch->mu);
    call->c.cq = cq;
    call->c.unary_armed = true;
    call->c.unary_tag = tag;
    call->c.internal_users++;
  }
  if (timed) {
    // Register before bytes leave (never touch `call` after the send
    // succeeds); notify so an already-parked tpr_cq_next recomputes its
    // wake time around the new deadline.
    std::lock_guard<std::mutex> lk(cq->mu);
    cq->timed_calls.insert(call);
    cq->cv.notify_all();
  }
  bool shipped;
  if (req_len <= kSmallUnaryMax) {
    shipped = ship_buffered(ch, call, hdr_payload, req, req_len);
  } else {
    shipped = ch->send_frame(kHeaders, 0, call->c.stream_id,
                             hdr_payload.data(), hdr_payload.size()) &&
              tpr_call_send(call, req, req_len, /*end_stream=*/1) == 0;
  }
  bool handed_off;
  {
    std::unique_lock<std::mutex> lk(ch->mu);
    call->c.internal_users--;
    // On failure: if die() already delivered the UNAVAILABLE completion,
    // hand the call back so the app's event handling destroys it;
    // otherwise suppress delivery and tear the call down ourselves.
    handed_off = shipped || (call->c.trailers_seen && !call->c.unary_armed);
    if (!handed_off) call->c.unary_armed = false;
  }
  ch->cv.notify_all();  // a destroy may be waiting on the pin drain
  if (handed_off) return call;
  if (timed) {
    // Mirror tpr_call_destroy's unhook for a call the app never saw: a
    // cq_next thread may hold it pinned mid-expiry.
    std::unique_lock<std::mutex> lk(cq->mu);
    cq->timed_calls.erase(call);
    cq->cv.wait_for(lk, std::chrono::seconds(30),
                    [&] { return call->c.cq_pins == 0; });
    if (call->c.cq_pins != 0) return nullptr;  // leak beats corruption
  }
  unregister_call(ch, call);
  return nullptr;
}

}  // extern "C"
