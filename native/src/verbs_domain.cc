// Verbs (RDMA NIC) memory domain — the hardware one-sided-placement
// skeleton (VERDICT r4 missing #3).
//
// The reference's product is the NIC writing the receive ring with zero
// receiver CPU: ibv_reg_mr'd buffers + RC queue pairs + RDMA WRITE
// (/root/reference/src/core/lib/ibverbs/pair.cc:587-622 postWrite,
// buffer.h:12-35, memory_region.h:14-47). tpurpc's architecture reaches
// hardware through its MemoryDomain seam instead (tpurpc/core/pair.py:
// Region/Window + register_domain): a domain allocates REGISTERED
// regions and opens one-sided write WINDOWS onto peer regions. This file
// is that domain's native half, redesigned for the seam rather than
// translated:
//
//   ctx  = device + protection domain + completion queue
//   mr   = a registered region (Region.buf's pinned backing store)
//   qp   = one RC connection to a peer (the Window's write engine)
//
// COMPILE GATING. This environment has no IB hardware or headers, so the
// real branch compiles only where <infiniband/verbs.h> exists; otherwise
// every entry point becomes an honest "unavailable" stub and
// tpr_verbs_available() returns 0 (the Python domain raises a clean
// RuntimeError naming the capability). CI still proves the real branch's
// CODE — tests compile this file against tests/mock_verbs/ (a minimal
// in-process verbs.h whose RDMA WRITE is a registry-backed memcpy) and
// drive a loopback one-sided write through the full call sequence.
//
// Rendezvous contract (mirrors the reference's Address: lid/qpn/psn/gid,
// address.h:24-31): tpr_verbs_qp_create returns the local attrs; the
// pair bootstrap ships them in its Address blob (the same JSON that
// carries shm handles today — core/pair.py Address.caps is the
// negotiation seam); tpr_verbs_qp_connect installs the peer's.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

// The real branch is enabled by the BUILD SYSTEM (TPR_HAVE_VERBS_LINKED,
// native/CMakeLists.txt: header AND libibverbs found, link flag added) —
// never by a bare __has_include, which on a header-only host would leave
// unresolved ibv_* symbols in libtpurpc.so and break ctypes loading of
// the whole native core.
#if defined(TPR_TEST_MOCK_VERBS)
#include "infiniband/verbs.h"  // the test's mock, via -I
#define TPR_HAVE_VERBS 1
#elif defined(TPR_HAVE_VERBS_LINKED)
#include <infiniband/verbs.h>
#define TPR_HAVE_VERBS 1
#else
#define TPR_HAVE_VERBS 0
#endif

#include <mutex>

extern "C" {

#if TPR_HAVE_VERBS

struct tpr_verbs_ctx {
  struct ibv_context *ctx;
  struct ibv_pd *pd;
  struct ibv_cq *cq;
  uint8_t port_num;
  uint16_t lid;
  union ibv_gid gid;
  // All this domain's QPs share one CQ, so completions are only
  // attributable while ONE signaled write is in flight: tpr_verbs_write
  // serializes under this (simple-correct; the reference pipelines
  // unsignaled writes per-QP instead, pair.cc postWrite — that is the
  // hardware-bringup optimization, not the skeleton's job).
  std::mutex write_mu;
};

struct tpr_verbs_mr {
  struct ibv_mr *mr;
  void *owned;  // non-null when we malloc'd the backing store
};

struct tpr_verbs_qp {
  tpr_verbs_ctx *c;
  struct ibv_qp *qp;
  uint32_t psn;
};

int tpr_verbs_available(void) { return 1; }

tpr_verbs_ctx *tpr_verbs_open(const char *dev_name) {
  int n = 0;
  struct ibv_device **list = ibv_get_device_list(&n);
  if (!list || n == 0) {
    if (list) ibv_free_device_list(list);
    return nullptr;
  }
  struct ibv_device *dev = list[0];
  if (dev_name && dev_name[0]) {
    dev = nullptr;
    for (int i = 0; i < n; ++i)
      if (strcmp(ibv_get_device_name(list[i]), dev_name) == 0) dev = list[i];
  }
  tpr_verbs_ctx *c = nullptr;
  if (dev) {
    c = new tpr_verbs_ctx();
    c->ctx = ibv_open_device(dev);
    c->port_num = 1;
    if (c->ctx) {
      c->pd = ibv_alloc_pd(c->ctx);
      // CQ depth 256: the domain posts signaled WRITEs and polls each —
      // the reference sizes its CQ to the pair count x pending writes
      c->cq = c->pd ? ibv_create_cq(c->ctx, 256, nullptr, nullptr, 0)
                    : nullptr;
      struct ibv_port_attr pa;
      if (c->cq && ibv_query_port(c->ctx, c->port_num, &pa) == 0)
        c->lid = pa.lid;
      ibv_query_gid(c->ctx, c->port_num, 0, &c->gid);
    }
    if (!c->ctx || !c->pd || !c->cq) {
      if (c->cq) ibv_destroy_cq(c->cq);
      if (c->pd) ibv_dealloc_pd(c->pd);
      if (c->ctx) ibv_close_device(c->ctx);
      delete c;
      c = nullptr;
    }
  }
  ibv_free_device_list(list);
  return c;
}

void tpr_verbs_close(tpr_verbs_ctx *c) {
  if (!c) return;
  if (c->cq) ibv_destroy_cq(c->cq);
  if (c->pd) ibv_dealloc_pd(c->pd);
  if (c->ctx) ibv_close_device(c->ctx);
  delete c;
}

tpr_verbs_mr *tpr_verbs_reg(tpr_verbs_ctx *c, void *addr, size_t len) {
  void *owned = nullptr;
  if (addr == nullptr) {
    // page-aligned allocation: reg_mr pins whole pages either way
    if (posix_memalign(&owned, 4096, len) != 0) return nullptr;
    memset(owned, 0, len);
    addr = owned;
  }
  struct ibv_mr *mr =
      ibv_reg_mr(c->pd, addr, len,
                 IBV_ACCESS_LOCAL_WRITE | IBV_ACCESS_REMOTE_WRITE);
  if (!mr) {
    free(owned);
    return nullptr;
  }
  auto *out = new tpr_verbs_mr();
  out->mr = mr;
  out->owned = owned;
  return out;
}

void *tpr_verbs_mr_addr(tpr_verbs_mr *m) { return m->mr->addr; }
uint64_t tpr_verbs_mr_len(tpr_verbs_mr *m) { return m->mr->length; }
uint32_t tpr_verbs_mr_lkey(tpr_verbs_mr *m) { return m->mr->lkey; }
uint32_t tpr_verbs_mr_rkey(tpr_verbs_mr *m) { return m->mr->rkey; }

void tpr_verbs_dereg(tpr_verbs_mr *m) {
  if (!m) return;
  void *owned = m->owned;
  ibv_dereg_mr(m->mr);
  free(owned);
  delete m;
}

// RC QP bring-up, reference shape (pair.cc init): create in RESET, move
// to INIT with write access. The RTR/RTS transitions happen in connect()
// once the peer's attrs arrive via the bootstrap blob.
tpr_verbs_qp *tpr_verbs_qp_create(tpr_verbs_ctx *c, uint32_t *qpn_out,
                                  uint16_t *lid_out, uint8_t gid_out[16],
                                  uint32_t *psn_out) {
  struct ibv_qp_init_attr ia;
  memset(&ia, 0, sizeof ia);
  ia.send_cq = c->cq;
  ia.recv_cq = c->cq;
  ia.qp_type = IBV_QPT_RC;
  ia.cap.max_send_wr = 128;
  ia.cap.max_recv_wr = 16;
  ia.cap.max_send_sge = 4;
  ia.cap.max_recv_sge = 1;
  struct ibv_qp *qp = ibv_create_qp(c->pd, &ia);
  if (!qp) return nullptr;
  struct ibv_qp_attr a;
  memset(&a, 0, sizeof a);
  a.qp_state = IBV_QPS_INIT;
  a.pkey_index = 0;
  a.port_num = c->port_num;
  a.qp_access_flags = IBV_ACCESS_LOCAL_WRITE | IBV_ACCESS_REMOTE_WRITE;
  if (ibv_modify_qp(qp, &a,
                    IBV_QP_STATE | IBV_QP_PKEY_INDEX | IBV_QP_PORT |
                        IBV_QP_ACCESS_FLAGS) != 0) {
    ibv_destroy_qp(qp);
    return nullptr;
  }
  auto *out = new tpr_verbs_qp();
  out->c = c;
  out->qp = qp;
  out->psn = (uint32_t)(rand() & 0xFFFFFF);
  *qpn_out = qp->qp_num;
  *lid_out = c->lid;
  memcpy(gid_out, c->gid.raw, 16);
  *psn_out = out->psn;
  return out;
}

int tpr_verbs_qp_connect(tpr_verbs_qp *q, uint32_t peer_qpn,
                         uint16_t peer_lid, const uint8_t peer_gid[16],
                         uint32_t peer_psn) {
  // INIT -> RTR (install the peer), reference pair.cc connect shape
  struct ibv_qp_attr a;
  memset(&a, 0, sizeof a);
  a.qp_state = IBV_QPS_RTR;
  a.path_mtu = IBV_MTU_1024;
  a.dest_qp_num = peer_qpn;
  a.rq_psn = peer_psn;
  a.max_dest_rd_atomic = 1;
  a.min_rnr_timer = 12;
  a.ah_attr.dlid = peer_lid;
  a.ah_attr.sl = 0;
  a.ah_attr.src_path_bits = 0;
  a.ah_attr.port_num = q->c->port_num;
  if (peer_lid == 0) {  // RoCE: route by GID instead of LID
    a.ah_attr.is_global = 1;
    memcpy(a.ah_attr.grh.dgid.raw, peer_gid, 16);
    a.ah_attr.grh.hop_limit = 64;
  }
  if (ibv_modify_qp(q->qp, &a,
                    IBV_QP_STATE | IBV_QP_AV | IBV_QP_PATH_MTU |
                        IBV_QP_DEST_QPN | IBV_QP_RQ_PSN |
                        IBV_QP_MAX_DEST_RD_ATOMIC | IBV_QP_MIN_RNR_TIMER) !=
      0)
    return -1;
  // RTR -> RTS (arm our send side)
  memset(&a, 0, sizeof a);
  a.qp_state = IBV_QPS_RTS;
  a.sq_psn = q->psn;
  a.timeout = 14;
  a.retry_cnt = 7;
  a.rnr_retry = 7;
  a.max_rd_atomic = 1;
  if (ibv_modify_qp(q->qp, &a,
                    IBV_QP_STATE | IBV_QP_SQ_PSN | IBV_QP_TIMEOUT |
                        IBV_QP_RETRY_CNT | IBV_QP_RNR_RETRY |
                        IBV_QP_MAX_QP_RD_ATOMIC) != 0)
    return -1;
  return 0;
}

// One one-sided write: post RDMA WRITE, poll its completion. The Window's
// write(offset, data) maps here with remote_addr = region base + offset
// (the reference's postWrite, pair.cc:587-622; it pipelines unsignaled
// writes — this skeleton signals every write, the simple-correct start).
int tpr_verbs_write(tpr_verbs_qp *q, const void *local, uint32_t lkey,
                    uint64_t remote_addr, uint32_t rkey, uint64_t len) {
  // one signaled write in flight per domain: the polled completion below
  // is provably OURS (see tpr_verbs_ctx::write_mu)
  std::lock_guard<std::mutex> lk(q->c->write_mu);
  struct ibv_sge sge;
  sge.addr = (uint64_t)(uintptr_t)local;
  sge.length = (uint32_t)len;
  sge.lkey = lkey;
  struct ibv_send_wr wr;
  memset(&wr, 0, sizeof wr);
  wr.wr_id = 1;
  wr.sg_list = &sge;
  wr.num_sge = 1;
  wr.opcode = IBV_WR_RDMA_WRITE;
  wr.send_flags = IBV_SEND_SIGNALED;
  wr.wr.rdma.remote_addr = remote_addr;
  wr.wr.rdma.rkey = rkey;
  struct ibv_send_wr *bad = nullptr;
  if (ibv_post_send(q->qp, &wr, &bad) != 0) return -1;
  struct ibv_wc wc;
  for (;;) {
    int n = ibv_poll_cq(q->c->cq, 1, &wc);
    if (n < 0) return -1;
    if (n == 1) return wc.status == IBV_WC_SUCCESS ? 0 : -1;
  }
}

void tpr_verbs_qp_destroy(tpr_verbs_qp *q) {
  if (!q) return;
  ibv_destroy_qp(q->qp);
  delete q;
}

#if defined(TPR_TEST_MOCK_VERBS)
// Test-only observability: how many MRs the mock "NIC" currently holds.
// Lets tests prove the registered-source post path really registers (and
// deregisters) its staging bounce MR rather than posting from raw memory.
int tpr_mock_mr_count(void) {
  auto &f = tpr_mock_fabric::get();
  std::lock_guard<std::mutex> lk(f.mu);
  return (int)f.mrs_by_rkey.size();
}
#endif  // TPR_TEST_MOCK_VERBS

#else  // !TPR_HAVE_VERBS — honest unavailability, never a silent fake

struct tpr_verbs_ctx;
struct tpr_verbs_mr;
struct tpr_verbs_qp;

int tpr_verbs_available(void) { return 0; }
tpr_verbs_ctx *tpr_verbs_open(const char *) { return nullptr; }
void tpr_verbs_close(tpr_verbs_ctx *) {}
tpr_verbs_mr *tpr_verbs_reg(tpr_verbs_ctx *, void *, size_t) {
  return nullptr;
}
void *tpr_verbs_mr_addr(tpr_verbs_mr *) { return nullptr; }
uint64_t tpr_verbs_mr_len(tpr_verbs_mr *) { return 0; }
uint32_t tpr_verbs_mr_lkey(tpr_verbs_mr *) { return 0; }
uint32_t tpr_verbs_mr_rkey(tpr_verbs_mr *) { return 0; }
void tpr_verbs_dereg(tpr_verbs_mr *) {}
tpr_verbs_qp *tpr_verbs_qp_create(tpr_verbs_ctx *, uint32_t *, uint16_t *,
                                  uint8_t *, uint32_t *) {
  return nullptr;
}
int tpr_verbs_qp_connect(tpr_verbs_qp *, uint32_t, uint16_t,
                         const uint8_t *, uint32_t) {
  return -1;
}
int tpr_verbs_write(tpr_verbs_qp *, const void *, uint32_t, uint64_t,
                    uint32_t, uint64_t) {
  return -1;
}
void tpr_verbs_qp_destroy(tpr_verbs_qp *) {}

#endif  // TPR_HAVE_VERBS

}  // extern "C"
