// Native rendezvous + ctrl rings (see tpr_rdv.h for the role overview).
// Byte layouts mirror tpurpc/core/rendezvous.py and tpurpc/core/ctrlring.py
// exactly — a Python peer and this C plane read each other's structs.
#include "tpr_rdv.h"

#include <pthread.h>
#include <sched.h>

#include "tpr_obs.h"

#include <algorithm>
#include <cstring>
#include <random>
#include <thread>

namespace tpr_rdv {

std::atomic<uint64_t> g_counters[kNumCounters] = {};

// -- env ---------------------------------------------------------------------

static bool env_off(const char *name) {
  const char *v = getenv(name);
  if (!v) return false;
  return strcmp(v, "0") == 0 || strcasecmp(v, "off") == 0 ||
         strcasecmp(v, "false") == 0;
}

bool enabled() { return !env_off("TPURPC_RENDEZVOUS"); }
bool ctrl_enabled() { return !env_off("TPURPC_CTRL_RING"); }

static uint64_t env_u64(const char *name, uint64_t dflt) {
  const char *v = getenv(name);
  if (!v) return dflt;
  char *end = nullptr;
  unsigned long long n = strtoull(v, &end, 10);
  return end == v ? dflt : (uint64_t)n;
}

uint64_t min_bytes() {
  uint64_t kb = env_u64("TPURPC_RENDEZVOUS_MIN_KB", 256);
  if (kb < 1) kb = 1;
  return kb * 1024;
}

uint64_t pool_budget() {
  uint64_t mb = env_u64("TPURPC_RENDEZVOUS_POOL_MB", 256);
  if (mb < 1) mb = 1;
  return mb << 20;
}

double claim_timeout_s() {
  const char *v = getenv("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S");
  if (!v) return 5.0;
  char *end = nullptr;
  double d = strtod(v, &end);
  return end == v ? 5.0 : d;
}

uint32_t ctrl_slots() {
  uint64_t n = env_u64("TPURPC_CTRL_RING_SLOTS", 64);
  if (n < 8) n = 8;
  return (uint32_t)n;
}

uint64_t size_class(uint64_t nbytes) {
  uint64_t c = kMinClass;
  while (c < nbytes) c <<= 1;
  return c;
}

// -- little helpers ----------------------------------------------------------

static uint64_t rd_u64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static uint32_t rd_u32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
static uint16_t rd_u16(const uint8_t *p) {
  uint16_t v;
  memcpy(&v, p, 2);
  return v;
}
static void put_u64(std::string &s, uint64_t v) {
  s.append(reinterpret_cast<const char *>(&v), 8);
}
static void put_u16s(std::string &s, uint16_t v) {
  s.append(reinterpret_cast<const char *>(&v), 2);
}
static void put_u32s(std::string &s, uint32_t v) {
  s.append(reinterpret_cast<const char *>(&v), 4);
}

static void fill_nonce(uint8_t *out) {
  static std::mutex mu;
  std::lock_guard<std::mutex> lk(mu);
  static std::mt19937_64 gen{std::random_device{}()};
  for (size_t i = 0; i < kNonceBytes; i += 8) {
    uint64_t r = gen();
    memcpy(out + i, &r, 8);
  }
}

static unsigned long self_tid() {
  return (unsigned long)pthread_self();
}

// TPURPC_RDV_DEBUG=1: stderr trace of the control ladder (dev aid only;
// the getenv is cached, flip it before process start)
static bool dbg_on() {
  static int v = -1;
  if (v < 0) {
    const char *e = getenv("TPURPC_RDV_DEBUG");
    v = (e && *e && strcmp(e, "0") != 0) ? 1 : 0;
  }
  return v == 1;
}
#define RDV_DBG(...)                                  \
  do {                                                \
    if (dbg_on()) {                                   \
      fprintf(stderr, "[rdv %s %lu] ", name_.c_str(), self_tid()); \
      fprintf(stderr, __VA_ARGS__);                   \
      fputc('\n', stderr);                           \
    }                                                 \
  } while (0)

// ---------------------------------------------------------------------------
// Landing pool: process-wide, shm regions pooled by size class under the
// byte budget. Region layout (offset 0 — the mmap base is page-aligned, so
// the 64 B alignment contract holds for free):
//   [payload: cls bytes][nonce: 16][doorbell: 8]
// The budget accounting constant (cls + 64 + 16 + 8) matches the Python
// pool's so the two planes exhaust comparably under one knob.
// ---------------------------------------------------------------------------

struct PoolRegion {
  tpr_ring::ShmRegion shm;
  uint64_t cls = 0;
  uint8_t nonce[kNonceBytes];

  // Consumer-freed count, read by the sender through its window — the
  // zero-frame "region reusable" signal. Release so the payload reads
  // that precede the free can't sink past the publish; the sender's
  // acquire read pairs with it.
  void doorbell_store(uint64_t v) {
    __atomic_store_n(reinterpret_cast<uint64_t *>(shm.base + cls +
                                                  kNonceBytes),
                     v, __ATOMIC_RELEASE);
  }
};

class Pool {
 public:
  static Pool &inst() {
    static Pool p;
    return p;
  }

  // Static-destruction sweep of the recycle cache: regions parked in the
  // free buckets are process-lifetime reuse capital, but they must still
  // unmap+unlink at exit (shm objects outlive the process otherwise, and
  // LeakSanitizer rightly flags the cached PoolRegions).
  ~Pool() {
    for (auto &kv : free_)
      for (PoolRegion *pr : kv.second) {
        pr->shm.close();
        delete pr;
      }
  }

  PoolRegion *lease(uint64_t cls) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = free_.find(cls);
      if (it != free_.end() && !it->second.empty()) {
        PoolRegion *pr = it->second.back();
        it->second.pop_back();
        pr->doorbell_store(0);  // fresh lease: no consumer history
        return pr;
      }
      uint64_t alloc = cls + 64 + kNonceBytes + 8;
      if (allocated_ + alloc > pool_budget()) return nullptr;
      allocated_ += alloc;
    }
    PoolRegion *pr = new PoolRegion();
    pr->cls = cls;
    if (!pr->shm.create(cls + kNonceBytes + 8)) {
      std::lock_guard<std::mutex> lk(mu_);
      allocated_ -= cls + 64 + kNonceBytes + 8;
      delete pr;
      return nullptr;
    }
    fill_nonce(pr->nonce);
    memcpy(pr->shm.base + cls, pr->nonce, kNonceBytes);
    return pr;
  }

  void recycle(PoolRegion *pr) {
    std::lock_guard<std::mutex> lk(mu_);
    free_[pr->cls].push_back(pr);
  }

  // Death-path quarantine: destroy, never re-lease — a straggling peer
  // window may still land a late one-sided write, which must hit the
  // orphaned shm object (its mapping stays valid on the writer's side
  // until IT closes), never a region re-leased to a new transfer.
  void discard(PoolRegion *pr) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      allocated_ -= pr->cls + 64 + kNonceBytes + 8;
    }
    pr->shm.close();
    delete pr;
  }

 private:
  std::mutex mu_;
  std::map<uint64_t, std::vector<PoolRegion *>> free_;
  uint64_t allocated_ = 0;
};

// ---------------------------------------------------------------------------
// Receiver-side lease (RegionLease mirror). Settlement state is shared
// between the delivering dispatch thread, whichever thread drops the last
// consumer reference (settle()), and the link's death path — hence the
// per-lease mutex and the single recycled transition.
// ---------------------------------------------------------------------------

struct Lease {
  std::mutex mu;
  uint64_t id = 0, cls = 0;
  PoolRegion *pr = nullptr;
  bool standing = false, pregrant = false;
  uint64_t delivered = 0, freed = 0;
  bool retired = false, discard = false, recycled = false;

  // The ONE recycle rule: back to the pool exactly once, when no further
  // delivery can happen AND no delivered buffer is still referenced.
  bool maybe_recycle_locked() {
    if (recycled) return false;
    bool done = retired || (delivered > 0 && !standing);
    if (done && freed == delivered) {
      recycled = true;
      return true;
    }
    return false;
  }

  void on_freed(uint64_t gen) {
    bool rec, disc, ring;
    {
      std::lock_guard<std::mutex> lk(mu);
      freed = std::max(freed, gen);
      rec = maybe_recycle_locked();
      disc = discard;
      ring = standing && !retired;
    }
    if (rec) {
      if (disc)
        Pool::inst().discard(pr);
      else
        Pool::inst().recycle(pr);
      pr = nullptr;
    } else if (ring) {
      pr->doorbell_store(gen);
    }
  }

  void release(bool disc) {
    bool rec, d;
    {
      std::lock_guard<std::mutex> lk(mu);
      retired = true;
      if (disc) discard = true;
      rec = maybe_recycle_locked();
      d = discard;
    }
    if (rec) {
      if (d)
        Pool::inst().discard(pr);
      else
        Pool::inst().recycle(pr);
      pr = nullptr;
    }
  }
};

// -- settle registry ---------------------------------------------------------

namespace {
struct SettleEntry {
  std::shared_ptr<Lease> lease;
  uint64_t gen;
};
std::mutex g_settle_mu;
std::unordered_map<const void *, SettleEntry> g_settle;
}  // namespace

bool settle(const void *ptr) {
  SettleEntry e;
  {
    std::lock_guard<std::mutex> lk(g_settle_mu);
    auto it = g_settle.find(ptr);
    if (it == g_settle.end()) return false;
    e = it->second;
    g_settle.erase(it);
  }
  e.lease->on_freed(e.gen);
  return true;
}

bool is_delivery(const void *ptr) {
  std::lock_guard<std::mutex> lk(g_settle_mu);
  return g_settle.count(ptr) != 0;
}

// -- sender-side claim -------------------------------------------------------

struct Claim {
  uint64_t lease_id = 0;
  std::string kind, handle;
  uint64_t offset = 0, capacity = 0;
  uint8_t nonce[kNonceBytes];
  bool standing = false;
  uint64_t used = 0;
  bool inflight = false;
};

// -- wire codecs (rendezvous.py _pack_*/_unpack_*) ---------------------------

static std::string pack_offer(uint64_t req, uint64_t nbytes) {
  std::string s;
  put_u64(s, req);
  put_u64(s, nbytes);
  s += "shm";  // kinds csv: the domains this sender can open windows of
  return s;
}

static std::string pack_claim_refused(uint64_t req) {
  std::string s;
  put_u64(s, req);
  put_u64(s, 0);
  s.push_back('\0');  // ok = 0
  return s;
}

static std::string pack_claim(uint64_t req, const Lease &lease) {
  std::string s;
  put_u64(s, req);
  put_u64(s, lease.id);
  s.push_back('\x01');                  // ok
  put_u64(s, 0);                        // offset (C regions: base-aligned)
  put_u64(s, lease.cls);                // capacity
  s.append(reinterpret_cast<const char *>(lease.pr->nonce), kNonceBytes);
  s.push_back(lease.standing ? '\x01' : '\0');
  s.push_back('\x03');                  // klen
  s += "shm";
  s += "shm:" + lease.pr->shm.name;     // Python-attachable handle
  return s;
}

static std::string pack_complete(uint64_t lease_id, uint64_t nbytes,
                                 uint8_t flags) {
  std::string s;
  put_u64(s, lease_id);
  put_u64(s, nbytes);
  s.push_back((char)flags);
  return s;
}

static std::string pack_release(uint64_t lease_id, uint64_t req) {
  std::string s;
  put_u64(s, lease_id);
  put_u64(s, req);
  return s;
}

// ---------------------------------------------------------------------------
// Link
// ---------------------------------------------------------------------------

// per-process link ordinal: makes every link's flight tags unique, so the
// per-link protocol machine keys (tag, lease)/(tag, req) never collide
// across links whose lease/req counters both start at 1
static std::atomic<uint64_t> g_link_ord{1};

Link::Link(const char *name) : name_(name ? name : "") {
  if (tpr_obs::enabled()) {
    uint64_t ord = g_link_ord.fetch_add(1, std::memory_order_relaxed);
    char tb[44];
    snprintf(tb, sizeof tb, "nrdv:%s#%llu", name_.c_str(),
             (unsigned long long)ord);
    otag_rdv_ = tpr_obs::tag_for(tb);
    snprintf(tb, sizeof tb, "nctrl:%s#%llu", name_.c_str(),
             (unsigned long long)ord);
    otag_ctrl_ = tpr_obs::tag_for(tb);
  }
  if (!enabled() || !ctrl_enabled()) return;
  // consumer-owned receive ring, advertised in our hello
  uint32_t nslots = ctrl_slots();
  size_t nbytes = kCtrlHdrBytes + (size_t)nslots * kCtrlSlotBytes;
  if (!rx_.shm.create(nbytes)) return;
  rx_.nslots = nslots;
  fill_nonce(rx_.nonce);
  uint8_t *b = rx_.shm.base;
  memcpy(b + 0, &kCtrlMagic, 4);
  uint32_t ver = kCtrlVersion, sb = kCtrlSlotBytes;
  memcpy(b + 4, &ver, 4);
  memcpy(b + 8, &nslots, 4);
  memcpy(b + 12, &sb, 4);
  // cons_head = 0 (fresh region is zeroed); parked = 1: nobody polls
  // until a dispatch loop adopts us (the producer kicks the first record)
  uint32_t parked = 1;
  memcpy(b + kParkedOff, &parked, 4);
  memcpy(b + kCtrlNonceOff, rx_.nonce, kNonceBytes);
  rx_inited_ = true;
}

Link::~Link() { close(); }

std::string Link::hello_payload() {
  std::string s(kHelloPayload, kHelloPayloadLen);
  if (!rx_inited_ || !ctrl_enabled()) return s;
  // _BLOB_LEN + _DESC(nslots, slot_bytes, nbytes, nonce, klen) + kind + handle
  std::string desc;
  put_u32s(desc, rx_.nslots);
  put_u32s(desc, kCtrlSlotBytes);
  put_u64(desc, (uint64_t)rx_.shm.len);
  desc.append(reinterpret_cast<const char *>(rx_.nonce), kNonceBytes);
  desc.push_back('\x03');
  desc += "shm";
  desc += "shm:" + rx_.shm.name;
  put_u16s(s, (uint16_t)desc.size());
  s += desc;
  return s;
}

bool Link::maybe_hello(const uint8_t *payload, size_t len) {
  if (len < kHelloPayloadLen ||
      memcmp(payload, kHelloPayload, kHelloPayloadLen) != 0)
    return false;
  negotiated.store(true);
  // trailing blob: the peer's receive-ring descriptor
  const uint8_t *blob = payload + kHelloPayloadLen;
  size_t blen = len - kHelloPayloadLen;
  if (blen < 2 + 33 || !ctrl_enabled() || ctrl_tx_open_.load()) return true;
  uint16_t dlen = rd_u16(blob);
  if ((size_t)dlen + 2 > blen) return true;
  const uint8_t *d = blob + 2;
  uint32_t nslots = rd_u32(d);
  uint32_t slot_bytes = rd_u32(d + 4);
  uint64_t nbytes = rd_u64(d + 8);
  uint8_t nonce[kNonceBytes];
  memcpy(nonce, d + 16, kNonceBytes);
  uint8_t klen = d[32];
  if (slot_bytes != kCtrlSlotBytes || nslots == 0 ||
      33u + klen >= dlen || nbytes > (64u << 20))
    return true;
  std::string kind(reinterpret_cast<const char *>(d + 33), klen);
  std::string handle(reinterpret_cast<const char *>(d + 33 + klen),
                     dlen - 33 - klen);
  if (kind != "shm" || handle.rfind("shm:", 0) != 0) return true;
  std::lock_guard<std::mutex> lk(tx_mu_);
  if (ctrl_tx_open_.load() || closed_.load()) return true;
  if (!tx_.shm.open(handle.substr(4), nbytes)) return true;
  // verify the descriptor resolves to the advertised memory
  uint8_t *b = tx_.shm.base;
  if (rd_u32(b) != kCtrlMagic || rd_u32(b + 4) != kCtrlVersion ||
      rd_u32(b + 8) != nslots || rd_u32(b + 12) != kCtrlSlotBytes ||
      memcmp(b + kCtrlNonceOff, nonce, kNonceBytes) != 0) {
    tx_.shm.close();
    return true;
  }
  tx_.nslots = nslots;
  tx_.seq = 0;
  ctrl_tx_open_.store(true);
  obs_adopted_.store(true, std::memory_order_relaxed);
  TPR_OBS(tpr_obs::kEvCtrlAdopt, otag_ctrl_, nslots, kCtrlSlotBytes);
  return true;
}

// -- control send ------------------------------------------------------------

void Link::ctrl_send(uint8_t op, uint32_t sid, const std::string &payload,
                     bool ring_ok) {
  if (ring_ok && ctrl_tx_open_.load() &&
      payload.size() <= kMaxCtrlPayload) {
    int r = 0;
    {
      std::lock_guard<std::mutex> lk(tx_mu_);
      if (ctrl_tx_open_.load()) {
        uint8_t *b = tx_.shm.base;
        uint64_t head = __atomic_load_n(
            reinterpret_cast<uint64_t *>(b + kConsHeadOff),
            __ATOMIC_ACQUIRE);
        if (tx_.seq - head >= tx_.nslots) {
          if (!tx_.stalled) {
            tx_.stalled = true;  // full: degrade framed, never overwrite
            TPR_OBS(tpr_obs::kEvCtrlStallBegin, otag_ctrl_,
                    tx_.seq - head, 0);
          }
        } else {
          if (tx_.stalled) {
            tx_.stalled = false;
            TPR_OBS(tpr_obs::kEvCtrlStallEnd, otag_ctrl_, 0, 0);
          }
          uint8_t *slot = b + kCtrlHdrBytes +
                          (tx_.seq % tx_.nslots) * kCtrlSlotBytes;
          // payload and fields FIRST...
          memcpy(slot + kCtrlSlotHdrBytes, payload.data(), payload.size());
          uint64_t fseq = frames_sent.load(std::memory_order_relaxed);
          memcpy(slot + 8, &fseq, 8);
          memcpy(slot + 16, &sid, 4);
          uint16_t ln = (uint16_t)payload.size();
          memcpy(slot + 20, &ln, 2);
          slot[22] = op;
          slot[23] = 0;
          // ...the stamp LAST (release): a consumer that observes it
          // observes a whole record
          __atomic_store_n(reinterpret_cast<uint64_t *>(slot),
                           tx_.seq + 1, __ATOMIC_RELEASE);
          tx_.seq++;
          // parked is read strictly AFTER the stamp store (the seq_cst
          // fence forbids the StoreLoad reorder): either the consumer's
          // park-then-redrain sees our record, or we see its parked flag
          // and kick — the lost-wakeup race has no third leg
          __atomic_thread_fence(__ATOMIC_SEQ_CST);
          uint32_t parked = __atomic_load_n(
              reinterpret_cast<uint32_t *>(b + kParkedOff),
              __ATOMIC_RELAXED);
          r = parked ? 2 : 1;
        }
      }
    }
    if (r) {
      RDV_DBG("ctrl_send op=%u sid=%u ring r=%d fseq=%llu", op, sid, r,
              (unsigned long long)frames_sent.load());
      count(kCtrCtrlPosts);
      tpr_obs::metric_add(tpr_obs::kMetCtrlPosts);
      if (r == 2) ctrl_kick();
      return;
    }
  }
  // framed fallback: one control frame (type = op + 7)
  RDV_DBG("ctrl_send op=%u sid=%u FRAMED (tx_open=%d len=%zu)", op, sid,
          (int)ctrl_tx_open_.load(), payload.size());
  count(kCtrCtrlFrames);
  tpr_obs::metric_add(tpr_obs::kMetCtrlFrames);
  if (send_frame) send_frame((uint8_t)(op + 7), sid, payload);
}

void Link::ctrl_kick() {
  count(kCtrCtrlKicks);
  tpr_obs::metric_add(tpr_obs::kMetCtrlKicks);
  if (send_frame) send_frame(12 /* kCtrlKick */, 0, std::string());
}

// -- ctrl consumer -----------------------------------------------------------

int Link::ctrl_drain() {
  if (!rx_inited_) return 0;
  // test seam (native_rdv_smoke's frozen-consumer stall): records age in
  // the ring, the Python producer's backlog gauge feeds the watchdog
  if (getenv("TPURPC_TEST_FREEZE_NCTRL")) return 0;
  if (!rx_mu_.try_lock()) return 0;
  int n = 0;
  uint8_t *b = rx_.shm.base;
  for (;;) {
    uint8_t *slot = b + kCtrlHdrBytes +
                    (rx_.head % rx_.nslots) * kCtrlSlotBytes;
    // stamp first, acquire: pairs with the producer's release store so
    // the field/payload reads below see a whole record
    uint64_t stamp = __atomic_load_n(reinterpret_cast<uint64_t *>(slot),
                                     __ATOMIC_ACQUIRE);
    if (stamp != rx_.head + 1) break;
    uint64_t fseq = rd_u64(slot + 8);
    if (fseq > frames_dispatched.load(std::memory_order_acquire)) {
      RDV_DBG("drain DEFER fseq=%llu dispatched=%llu head=%llu",
              (unsigned long long)fseq,
              (unsigned long long)frames_dispatched.load(),
              (unsigned long long)rx_.head);
      break;  // ordered after frames still in flight
    }
    uint32_t sid = rd_u32(slot + 16);
    uint16_t ln = rd_u16(slot + 20);
    uint8_t op = slot[22];
    uint8_t payload[kMaxCtrlPayload];
    if (ln > kMaxCtrlPayload) ln = kMaxCtrlPayload;
    memcpy(payload, slot + kCtrlSlotHdrBytes, ln);
    rx_.head++;
    on_op(op, sid, payload, ln);
    ++n;
  }
  uint64_t head_now = rx_.head;
  if (n) {
    // ONE cons_head publish per drained batch (release: our payload
    // reads can't sink past the producer's licence to reuse the slots)
    __atomic_store_n(reinterpret_cast<uint64_t *>(b + kConsHeadOff),
                     (uint64_t)rx_.head, __ATOMIC_RELEASE);
  }
  rx_mu_.unlock();
  if (n) {
    count(kCtrCtrlRecords, (uint64_t)n);
    tpr_obs::metric_add(tpr_obs::kMetCtrlDrainBatches);
    tpr_obs::metric_add(tpr_obs::kMetCtrlDrainRecords, (uint64_t)n);
    std::lock_guard<std::mutex> lk(ewma_mu_);
    ewma_ = ewma_ + 0.5 * (1.0 - ewma_);  // _EWMA_HIT
    if (!mode_hot_) {
      mode_hot_ = true;
      uint32_t v = 0;
      __atomic_store_n(reinterpret_cast<uint32_t *>(b + kParkedOff), v,
                       __ATOMIC_RELEASE);
      if (obs_adopted_.load(std::memory_order_relaxed))
        TPR_OBS(tpr_obs::kEvCtrlSpin, otag_ctrl_, head_now, 0);
    }
  }
  return n;
}

bool Link::ctrl_hot() {
  std::lock_guard<std::mutex> lk(ewma_mu_);
  return mode_hot_;
}

void Link::ctrl_decay() {
  std::lock_guard<std::mutex> lk(ewma_mu_);
  ewma_ *= 0.7;  // _EWMA_MISS
  if (ewma_ < 0.1) mode_hot_ = false;
}

void Link::ctrl_park() {
  if (!rx_inited_) return;
  bool was_hot;
  {
    std::lock_guard<std::mutex> lk(ewma_mu_);
    was_hot = mode_hot_;
    mode_hot_ = false;
  }
  if (was_hot && obs_adopted_.load(std::memory_order_relaxed)) {
    uint64_t h;
    {
      std::lock_guard<std::mutex> lk(rx_mu_);
      h = rx_.head;
    }
    TPR_OBS(tpr_obs::kEvCtrlPark, otag_ctrl_, h, 0);
  }
  uint32_t v = 1;
  __atomic_store_n(reinterpret_cast<uint32_t *>(rx_.shm.base + kParkedOff),
                   v, __ATOMIC_RELEASE);
  // the mandatory re-drain: ordered AFTER the parked store (seq_cst
  // fence) so a record stamped concurrently is either seen here or its
  // producer sees parked=1 and kicks
  __atomic_thread_fence(__ATOMIC_SEQ_CST);
  ctrl_drain();
}

// -- dispatch ----------------------------------------------------------------

bool Link::on_frame(uint8_t type, uint32_t sid, const uint8_t *p,
                    size_t len) {
  if (type >= 8 && type <= 11) {
    on_op((uint8_t)(type - 7), sid, p, len);
    return true;
  }
  if (type == 12) {  // CTRL_KICK: the wake is the fd readiness itself
    ctrl_drain();
    return true;
  }
  return false;
}

void Link::on_op(uint8_t op, uint32_t sid, const uint8_t *p, size_t len) {
  switch (op) {
    case kOpOffer:
      on_offer(sid, p, len);
      break;
    case kOpClaim:
      on_claim(p, len);
      break;
    case kOpComplete:
      on_complete(sid, p, len);
      break;
    case kOpRelease:
      on_release(p, len);
      break;
    default:
      break;  // malformed control degrades, never kills the connection
  }
}

// -- sender role -------------------------------------------------------------

void Link::set_dispatch_thread() { dispatch_tid_.store(self_tid()); }

bool Link::eligible(size_t total) const {
  return negotiated.load() && !closed_.load() && enabled() &&
         total >= min_bytes() && total <= kMaxTransfer &&
         self_tid() != dispatch_tid_.load();
}

uint8_t *Link::window_base(const std::string &handle, size_t nbytes) {
  if (handle.rfind("shm:", 0) != 0) return nullptr;
  std::lock_guard<std::mutex> lk(mu_);
  if (closed_.load()) return nullptr;
  auto it = windows_.find(handle);
  if (it != windows_.end()) return it->second.base;
  tpr_ring::ShmRegion win;
  if (!win.open(handle.substr(4), nbytes)) return nullptr;
  uint8_t *base = win.base;
  windows_.emplace(handle, win);
  return base;
}

bool Link::pin_windows() {
  window_pins_.fetch_add(1, std::memory_order_seq_cst);
  if (closed_.load(std::memory_order_seq_cst)) {
    window_pins_.fetch_sub(1, std::memory_order_seq_cst);
    return false;
  }
  return true;
}

void Link::unpin_windows() {
  window_pins_.fetch_sub(1, std::memory_order_seq_cst);
}

bool Link::standing_free(const std::shared_ptr<Claim> &c) {
  if (!pin_windows()) return false;
  uint8_t *base = window_base(
      c->handle, c->offset + c->capacity + kNonceBytes + 8);
  bool free_now = false;
  if (base) {
    uint64_t freed = __atomic_load_n(
        reinterpret_cast<uint64_t *>(base + c->offset + c->capacity +
                                     kNonceBytes),
        __ATOMIC_ACQUIRE);
    free_now = freed == c->used;
  }
  unpin_windows();
  return free_now;
}

std::shared_ptr<Claim> Link::take_grant(uint64_t cls, size_t total) {
  std::vector<std::shared_ptr<Claim>> bucket;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (closed_.load()) return nullptr;
    auto it = grants_.find(cls);
    if (it != grants_.end()) bucket = it->second;
  }
  for (auto &c : bucket) {
    if (c->capacity < total) continue;
    if (!c->standing) {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = grants_.find(cls);
      if (it != grants_.end()) {
        auto pos = std::find(it->second.begin(), it->second.end(), c);
        if (pos != it->second.end()) {
          it->second.erase(pos);
          return c;  // one-shot: consumed
        }
      }
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (c->inflight) continue;
      c->inflight = true;
    }
    if (standing_free(c)) return c;
    std::lock_guard<std::mutex> lk(mu_);
    c->inflight = false;
  }
  return nullptr;
}

bool Link::has_standing(uint64_t cls, size_t total) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = grants_.find(cls);
  if (it == grants_.end()) return false;
  for (auto &c : it->second)
    if (c->standing && c->capacity >= total) return true;
  return false;
}

void Link::drop_grant(const std::shared_ptr<Claim> &c) {
  std::lock_guard<std::mutex> lk(mu_);
  c->inflight = false;
  auto it = grants_.find(size_class(c->capacity));
  if (it != grants_.end()) {
    auto pos = std::find(it->second.begin(), it->second.end(), c);
    if (pos != it->second.end()) it->second.erase(pos);
  }
}

std::shared_ptr<Claim> Link::rdv_claim(uint32_t sid, size_t total,
                                       uint64_t cls) {
  (void)cls;
  uint64_t req;
  auto pr = std::make_shared<PendingReq>();
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (closed_.load()) return nullptr;
    req = next_req_++;
    reqs_[req] = pr;
  }
  RDV_DBG("rdv_claim OFFER req=%llu total=%zu", (unsigned long long)req,
          total);
  TPR_OBS(tpr_obs::kEvRdvOffer, otag_rdv_, req, total);
  tpr_obs::metric_add(tpr_obs::kMetRdvWaits);
  uint64_t wait_t0 = tpr_obs::now_ns();
  ctrl_send(kOpOffer, sid, pack_offer(req, total));
  auto dl = std::chrono::steady_clock::now() +
            std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                std::chrono::duration<double>(claim_timeout_s()));
  if (pump) {
    // inline-pump transports: the waiting sender drives the reader itself
    pump([&] {
      std::lock_guard<std::mutex> lk(mu_);
      return pr->state != 0 || closed_.load();
    }, dl);
  } else {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait_until(lk, dl,
                   [&] { return pr->state != 0 || closed_.load(); });
  }
  int state;
  std::shared_ptr<Claim> claim;
  {
    std::lock_guard<std::mutex> lk(mu_);
    reqs_.erase(req);
    state = pr->state;
    claim = pr->claim;
  }
  tpr_obs::metric_add(tpr_obs::kMetRdvWaitNs,
                      tpr_obs::now_ns() - wait_t0);
  if (state == 0) {
    RDV_DBG("rdv_claim TIMEOUT req=%llu", (unsigned long long)req);
    // timed out: abandon the offer — a claim crossing this release finds
    // no pending request and is released by on_claim's unknown-req path
    TPR_OBS(tpr_obs::kEvRdvRelease, otag_rdv_, 0, req);
    ctrl_send(kOpRelease, 0, pack_release(0, req));
    return nullptr;
  }
  if (state == 1 && claim)
    TPR_OBS(tpr_obs::kEvRdvClaim, otag_rdv_, req, claim->lease_id);
  return state == 1 ? claim : nullptr;
}

bool Link::rdv_write(const std::shared_ptr<Claim> &c, const uint8_t *data,
                     size_t total) {
  // pinned for the whole deref span: the bulk memcpy runs without mu_, and
  // a concurrent close() (transport death seen by the pumping thread)
  // would otherwise munmap the window mid-copy — observed as a SEGV, or
  // worse, a silent 1 MiB scribble over whatever mapping reused the range
  if (!pin_windows()) return false;
  bool ok = false;
  uint8_t *base = window_base(
      c->handle, c->offset + c->capacity + kNonceBytes + 8);
  // anti-mixup nonce: the claimed handle must resolve to the memory the
  // receiver advertised, never a recycled name
  if (base != nullptr &&
      memcmp(base + c->offset + c->capacity, c->nonce, kNonceBytes) == 0) {
    if (tpr_obs::enabled()) {
      uint64_t t0 = tpr_obs::now_ns();
      memcpy(base + c->offset, data, total);  // the one-sided placement
      tpr_obs::metric_add(tpr_obs::kMetRdvSendBusyNs,
                          tpr_obs::now_ns() - t0);
      tpr_obs::metric_add(tpr_obs::kMetRdvSendBytes, total);
    } else {
      memcpy(base + c->offset, data, total);  // the one-sided placement
    }
    count(kCtrRdvBytesSent, total);
    ok = true;
  }
  unpin_windows();
  return ok;
}

void Link::rdv_complete(const std::shared_ptr<Claim> &c, uint32_t sid,
                        uint8_t flags, size_t total) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    c->used++;
    c->inflight = false;
  }
  if (!c->standing) {
    // solicited transfers are edges worth recording; standing-region
    // reuse is steady-state traffic and stays silent (the flight
    // recorder's edges-not-traffic contract — rendezvous.py's rule)
    TPR_OBS(tpr_obs::kEvRdvWrite, otag_rdv_, c->lease_id, total);
    TPR_OBS(tpr_obs::kEvRdvComplete, otag_rdv_, c->lease_id, total);
  }
  // shm windows are synchronous (the memcpy returned ⇒ bytes visible), so
  // the COMPLETE may ride the ring
  ctrl_send(kOpComplete, sid, pack_complete(c->lease_id, total, flags));
}

void Link::rdv_release(const std::shared_ptr<Claim> &c) {
  TPR_OBS(tpr_obs::kEvRdvRelease, otag_rdv_, c->lease_id, 0);
  ctrl_send(kOpRelease, 0, pack_release(c->lease_id, 0));
}

bool Link::send_message(uint32_t sid, uint8_t flags, const uint8_t *data,
                        size_t total) {
  uint64_t cls = size_class(total);
  auto claim = take_grant(cls, total);
  if (!claim && has_standing(cls, total)) {
    // every standing region's doorbell is behind — the consumer is
    // mid-batch. A bounded yield-poll (draining our ctrl ring for
    // pregrant top-ups as we go) almost always turns up a freed region
    // in a few slices, cheaper than a solicited-claim round trip.
    auto dl = std::chrono::steady_clock::now() +
              std::chrono::milliseconds(2);
    while (!claim && std::chrono::steady_clock::now() < dl) {
      ctrl_drain();
      sched_yield();
      claim = take_grant(cls, total);
    }
  }
  if (!claim) claim = rdv_claim(sid, total, cls);
  if (!claim) {
    count(kCtrRdvFallback);
    tpr_obs::metric_add(tpr_obs::kMetRdvFallbacks);
    TPR_OBS(tpr_obs::kEvRdvFallback, otag_rdv_, total, 0);
    return false;
  }
  if (!rdv_write(claim, data, total)) {
    drop_grant(claim);
    rdv_release(claim);
    count(kCtrRdvFallback);
    tpr_obs::metric_add(tpr_obs::kMetRdvFallbacks);
    TPR_OBS(tpr_obs::kEvRdvFallback, otag_rdv_, total, 1);
    return false;
  }
  rdv_complete(claim, sid, flags, total);
  count(kCtrRdvSent);
  return true;
}

// -- receiver role -----------------------------------------------------------

void Link::on_offer(uint32_t sid, const uint8_t *p, size_t len) {
  if (len < 16) return;
  uint64_t req = rd_u64(p);
  uint64_t nbytes = rd_u64(p + 8);
  TPR_OBS(tpr_obs::kEvRdvOffer, otag_rdv_, req, nbytes);
  std::string kinds(reinterpret_cast<const char *>(p + 16), len - 16);
  bool shm_ok = false;
  size_t pos = 0;
  while (pos <= kinds.size()) {
    size_t comma = kinds.find(',', pos);
    std::string k = kinds.substr(
        pos, comma == std::string::npos ? std::string::npos : comma - pos);
    if (k == "shm") shm_ok = true;
    if (comma == std::string::npos) break;
    pos = comma + 1;
  }
  std::shared_ptr<Lease> lease;
  if (shm_ok && enabled() && nbytes <= kMaxTransfer && !closed_.load()) {
    PoolRegion *pr = Pool::inst().lease(size_class(nbytes));
    if (pr) {
      lease = std::make_shared<Lease>();
      lease->pr = pr;
      lease->cls = pr->cls;
    }
  }
  if (!lease) {
    count(kCtrRdvRefused);
    ctrl_send(kOpClaim, sid, pack_claim_refused(req));
    return;
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (closed_.load()) {
      lease->release(false);
      return;
    }
    lease->id = next_lease_++;
    leases_[lease->id] = lease;
    req_lease_[req] = lease->id;
  }
  RDV_DBG("on_offer req=%llu -> CLAIM lease=%llu cls=%llu standing=%d",
          (unsigned long long)req, (unsigned long long)lease->id,
          (unsigned long long)lease->cls, (int)lease->standing);
  TPR_OBS(tpr_obs::kEvRdvClaim, otag_rdv_, req, lease->id);
  ctrl_send(kOpClaim, sid, pack_claim(req, *lease));
}

void Link::on_claim(const uint8_t *p, size_t len) {
  if (len < 17) return;
  uint64_t req = rd_u64(p);
  uint64_t lease_id = rd_u64(p + 8);
  uint8_t ok = p[16];
  RDV_DBG("on_claim req=%llu lease=%llu ok=%d",
          (unsigned long long)req, (unsigned long long)lease_id, (int)ok);
  std::shared_ptr<Claim> claim;
  if (ok) {
    // _CLAIM_REG: offset, capacity, nonce, standing; then klen, kind, handle
    if (len < 17 + 33 + 1) return;
    claim = std::make_shared<Claim>();
    claim->lease_id = lease_id;
    claim->offset = rd_u64(p + 17);
    claim->capacity = rd_u64(p + 25);
    memcpy(claim->nonce, p + 33, kNonceBytes);
    claim->standing = p[49] != 0;
    uint8_t klen = p[50];
    if (51u + klen > len) return;
    claim->kind.assign(reinterpret_cast<const char *>(p + 51), klen);
    claim->handle.assign(reinterpret_cast<const char *>(p + 51 + klen),
                         len - 51 - klen);
    if (claim->kind != "shm" || claim->capacity == 0 ||
        claim->capacity > kMaxTransfer)
      return;
  }
  if (req == 0) {
    // unsolicited pre-grant: cache for the next same-class send
    if (claim) {
      std::lock_guard<std::mutex> lk(mu_);
      if (!closed_.load())
        grants_[claim->capacity].push_back(claim);
    }
    return;
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = reqs_.find(req);
    if (it != reqs_.end()) {
      it->second->state = claim ? 1 : 2;
      it->second->claim = claim;
      cv_.notify_all();
      claim = nullptr;  // ownership passed to the waiter
    }
  }
  if (wake) wake();
  // the sender already gave up (timeout crossed the claim on the wire):
  // hand the region straight back
  if (claim) ctrl_send(kOpRelease, 0, pack_release(claim->lease_id, 0));
}

void Link::on_complete(uint32_t sid, const uint8_t *p, size_t len) {
  if (len < 17) return;
  uint64_t lease_id = rd_u64(p);
  uint64_t nbytes = rd_u64(p + 8);
  uint8_t flags = p[16];
  RDV_DBG("on_complete lease=%llu nbytes=%llu",
          (unsigned long long)lease_id, (unsigned long long)nbytes);
  std::shared_ptr<Lease> lease;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = leases_.find(lease_id);
    if (it == leases_.end()) return;  // crossed a release — drop
    lease = it->second;
    if (!lease->standing) {
      // one-shot lease: consumed by this completion; standing leases
      // stay claimed (the doorbell carries further reuse)
      leases_.erase(it);
      for (auto r = req_lease_.begin(); r != req_lease_.end();) {
        if (r->second == lease_id)
          r = req_lease_.erase(r);
        else
          ++r;
      }
    }
  }
  uint64_t gen = 0;
  bool violation = false;
  {
    std::lock_guard<std::mutex> lg(lease->mu);
    if (lease->retired || (lease->delivered && !lease->standing) ||
        nbytes > lease->cls ||
        (lease->standing && lease->delivered != lease->freed)) {
      // oversized complete, or reuse while the previous delivery is
      // still aliased — refuse rather than hand out a second alias
      violation = true;
    } else {
      lease->delivered++;
      gen = lease->delivered;
    }
  }
  if (violation) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      leases_.erase(lease_id);
      if (lease->pregrant) {
        auto pg = pregrants_out_.find(lease->cls);
        if (pg != pregrants_out_.end() && pg->second > 0) pg->second--;
      }
    }
    lease->release(true);  // a confused sender may write again: discard
    return;
  }
  uint8_t *base = lease->pr->shm.base;
  {
    std::lock_guard<std::mutex> lk(g_settle_mu);
    g_settle[base] = SettleEntry{lease, gen};
  }
  count(kCtrRdvRecv);
  count(kCtrRdvBytesRecv, nbytes);
  if (!lease->pregrant)
    TPR_OBS(tpr_obs::kEvRdvComplete, otag_rdv_, lease_id, nbytes);
  uint64_t cls = lease->cls;
  if (deliver) {
    if (tpr_obs::enabled()) {
      uint64_t t0 = tpr_obs::now_ns();
      deliver(sid, flags, base, (size_t)nbytes);
      tpr_obs::metric_add(tpr_obs::kMetRdvRecvBusyNs,
                          tpr_obs::now_ns() - t0);
      tpr_obs::metric_add(tpr_obs::kMetRdvRecvBytes, nbytes);
    } else {
      deliver(sid, flags, base, (size_t)nbytes);
    }
  } else {
    settle(base);  // no consumer wired: drop, ring the doorbell
  }
  maybe_pregrant(cls);
}

void Link::maybe_pregrant(uint64_t cls) {
  // RDMAbox discipline: keep STANDING regions granted for the classes the
  // peer is actively streaming, topped up to kPregrantDepth — a standing
  // grant costs one claim frame EVER; reuse rides the doorbell word.
  for (;;) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (closed_.load() || pregrants_out_[cls] >= kPregrantDepth) return;
    }
    PoolRegion *pr = Pool::inst().lease(cls);
    if (!pr) return;
    auto lease = std::make_shared<Lease>();
    lease->pr = pr;
    lease->cls = cls;
    lease->standing = true;
    lease->pregrant = true;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (closed_.load()) {
        lease->release(false);
        return;
      }
      lease->id = next_lease_++;
      leases_[lease->id] = lease;
      pregrants_out_[cls]++;
    }
    count(kCtrPregrants);
    ctrl_send(kOpClaim, 0, pack_claim(0, *lease));
  }
}

void Link::on_release(const uint8_t *p, size_t len) {
  if (len < 16) return;
  uint64_t lease_id = rd_u64(p);
  uint64_t req = rd_u64(p + 8);
  std::shared_ptr<Lease> lease;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!lease_id && req) {
      auto it = req_lease_.find(req);
      if (it != req_lease_.end()) {
        lease_id = it->second;
        req_lease_.erase(it);
      }
    }
    auto it = leases_.find(lease_id);
    if (it != leases_.end()) {
      lease = it->second;
      leases_.erase(it);
      if (lease->pregrant) {
        auto pg = pregrants_out_.find(lease->cls);
        if (pg != pregrants_out_.end() && pg->second > 0) pg->second--;
      }
    }
  }
  if (lease) {
    TPR_OBS(tpr_obs::kEvRdvRelease, otag_rdv_, lease_id, req);
    lease->release(false);
  }
}

// -- lifecycle ---------------------------------------------------------------

void Link::close() {
  std::vector<std::shared_ptr<Lease>> leases;
  std::vector<tpr_ring::ShmRegion> wins;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (closed_.exchange(true)) return;
    for (auto &kv : leases_) leases.push_back(kv.second);
    leases_.clear();
    req_lease_.clear();
    pregrants_out_.clear();
    grants_.clear();
    for (auto &kv : windows_) wins.push_back(kv.second);
    windows_.clear();
    cv_.notify_all();
  }
  if (wake) wake();
  for (auto &lease : leases) {
    // DISCARD, don't pool: the peer (or a straggling sender on this
    // dying connection) may still hold a window and land a late write —
    // it must hit orphaned memory, never a re-leased region; teardown is
    // an EDGE (once per connection death), so every claimed region's
    // release is recorded — standing grants included
    TPR_OBS(tpr_obs::kEvRdvRelease, otag_rdv_, lease->id, 0);
    lease->release(true);
  }
  // Straggling senders may still be inside rdv_write's memcpy with a raw
  // window pointer (pinned): wait for every pin to drain before the
  // munmap. Bounded — a pin only spans a memcpy or one doorbell load.
  int pins = window_pins_.load(std::memory_order_seq_cst);
  if (pins != 0) {
    uint64_t t0 = tpr_obs::now_ns();
    TPR_OBS(tpr_obs::kEvPinWaitBegin, otag_rdv_, pins, 0);
    tpr_obs::metric_add(tpr_obs::kMetPinWaits);
    while (window_pins_.load(std::memory_order_seq_cst) != 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    uint64_t waited = tpr_obs::now_ns() - t0;
    tpr_obs::metric_add(tpr_obs::kMetPinWaitNs, waited);
    TPR_OBS(tpr_obs::kEvPinWaitEnd, otag_rdv_, waited, 0);
  }
  for (auto &w : wins) w.close();
  {
    std::lock_guard<std::mutex> lk(tx_mu_);
    if (ctrl_tx_open_.exchange(false)) tx_.shm.close();
  }
  {
    std::lock_guard<std::mutex> lk(rx_mu_);
    if (rx_inited_) {
      rx_inited_ = false;
      rx_.shm.close();  // a late producer store hits the orphaned mapping
    }
  }
}

}  // namespace tpr_rdv

// -- C ABI: the process-wide ledger the shim and tests read ------------------

extern "C" {

void tpr_rdv_counters(uint64_t *out, int n) {
  for (int i = 0; i < n && i < tpr_rdv::kNumCounters; i++)
    out[i] = tpr_rdv::g_counters[i].load(std::memory_order_relaxed);
}

void tpr_rdv_counters_reset(void) {
  for (auto &c : tpr_rdv::g_counters) c.store(0, std::memory_order_relaxed);
}

}  // extern "C"
