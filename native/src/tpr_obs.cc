// tpurpc-xray: shm flight ring + metrics table (layout and protocol in
// tpr_obs.h; the Python-side decoder is tpurpc/obs/native_obs.py).
#include "tpr_obs.h"

#include <pthread.h>
#include <sched.h>
#include <string.h>
#include <time.h>

#include <mutex>

#include "ring_transport.h"

namespace tpr_obs {

namespace {

bool env_off(const char *name) {
  const char *v = getenv(name);
  if (!v) return false;
  return strcmp(v, "0") == 0 || strcasecmp(v, "off") == 0 ||
         strcasecmp(v, "false") == 0;
}

uint32_t ring_capacity() {
  const char *v = getenv("TPURPC_NATIVE_OBS_BUFFER");
  if (v && *v) {
    char *end = nullptr;
    unsigned long n = strtoul(v, &end, 10);
    if (end != v && n >= 64) return (uint32_t)n;
  }
  return 4096;
}

struct State {
  tpr_ring::ShmRegion shm;
  uint32_t capacity = 0;
  uint64_t *ticket = nullptr;    // header word
  uint32_t *tag_count = nullptr; // header word
  uint64_t *metrics = nullptr;
  uint8_t *tags = nullptr;
  uint64_t *seq = nullptr;
  uint64_t *recs = nullptr;      // capacity * 4 words
};

std::mutex g_init_mu;   // init / intern / reset only — never on emit
State *g_state = nullptr;  // set once under g_init_mu, read lock-free
bool g_init_done = false;

State *build_state() {
  uint32_t cap = ring_capacity();
  uint32_t metrics_off = kHdrBytes;
  uint32_t tags_off = metrics_off + (uint32_t)kNumMetrics * 8;
  uint32_t seq_off = tags_off + kTagCap * kTagBytes;
  uint32_t rec_off = seq_off + cap * 8;
  size_t nbytes = (size_t)rec_off + (size_t)cap * kRecordBytes;
  State *st = new State();
  if (!st->shm.create(nbytes)) {
    delete st;
    return nullptr;
  }
  uint8_t *b = st->shm.base;
  uint32_t ver = kObsVersion, tag_cap = kTagCap,
           nmet = (uint32_t)kNumMetrics, rb = kRecordBytes,
           magic = kObsMagic;
  memcpy(b + kHdrMagic, &magic, 4);
  memcpy(b + kHdrVersion, &ver, 4);
  memcpy(b + kHdrCapacity, &cap, 4);
  memcpy(b + kHdrTagCap, &tag_cap, 4);
  memcpy(b + kHdrMetricsCap, &nmet, 4);
  memcpy(b + kHdrRecordBytes, &rb, 4);
  memcpy(b + kHdrMetricsOff, &metrics_off, 4);
  memcpy(b + kHdrTagsOff, &tags_off, 4);
  memcpy(b + kHdrSeqOff, &seq_off, 4);
  memcpy(b + kHdrRecOff, &rec_off, 4);
  st->capacity = cap;
  st->ticket = reinterpret_cast<uint64_t *>(b + kHdrTicket);
  st->tag_count = reinterpret_cast<uint32_t *>(b + kHdrTagCount);
  st->metrics = reinterpret_cast<uint64_t *>(b + metrics_off);
  st->tags = b + tags_off;
  st->seq = reinterpret_cast<uint64_t *>(b + seq_off);
  st->recs = reinterpret_cast<uint64_t *>(b + rec_off);
  return st;
}

// Lock-free fast path: after the one guarded init, readers see either
// nullptr (off / failed) or a fully built State through the acquire load.
State *state() {
  if (__atomic_load_n(&g_init_done, __ATOMIC_ACQUIRE))
    return __atomic_load_n(&g_state, __ATOMIC_RELAXED);
  std::lock_guard<std::mutex> lk(g_init_mu);
  if (!g_init_done) {
    if (!env_off("TPURPC_NATIVE_OBS"))
      __atomic_store_n(&g_state, build_state(), __ATOMIC_RELAXED);
    __atomic_store_n(&g_init_done, true, __ATOMIC_RELEASE);
  }
  return g_state;
}

}  // namespace

bool enabled() { return state() != nullptr; }

uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

uint16_t tag_for(const char *name) {
  State *st = state();
  if (!st || !name) return 0;
  size_t len = strlen(name);
  if (len > kTagBytes - 2) len = kTagBytes - 2;
  std::lock_guard<std::mutex> lk(g_init_mu);
  uint32_t n = __atomic_load_n(st->tag_count, __ATOMIC_RELAXED);
  for (uint32_t i = 0; i < n && i < kTagCap; i++) {
    uint8_t *slot = st->tags + (size_t)i * kTagBytes;
    uint16_t slen;
    memcpy(&slen, slot, 2);
    if (slen == len && memcmp(slot + 2, name, len) == 0)
      return (uint16_t)(i + 1);
  }
  if (n >= kTagCap) {
    metric_add(kMetTagOverflow);
    return 0;  // degrade to the anonymous tag, never an error
  }
  uint8_t *slot = st->tags + (size_t)n * kTagBytes;
  memcpy(slot + 2, name, len);
  uint16_t slen = (uint16_t)len;
  memcpy(slot, &slen, 2);
  // count publishes AFTER the name bytes: a concurrent reader that sees
  // slot i < count sees a whole name
  __atomic_store_n(st->tag_count, n + 1, __ATOMIC_RELEASE);
  return (uint16_t)(n + 1);
}

void emit(uint16_t code, uint16_t tag, int64_t a1, int64_t a2) {
  State *st = state();
  if (!st) return;
  uint64_t ticket = __atomic_fetch_add(st->ticket, 1, __ATOMIC_RELAXED);
  uint32_t slot = (uint32_t)(ticket % st->capacity);
  uint64_t *r = st->recs + (size_t)slot * 4;
  // Claim the slot: wait for the previous-lap tenant (ticket - capacity)
  // to have published. Without this, a writer that lags a FULL ring lap
  // behind a wrapping peer could interleave word stores on the same slot
  // and the later stamp would mask the tear from readers (both stamps are
  // nonzero and stable). The wait only ever fires in that one-lap-behind
  // case — the hot path is a single acquire load that matches.
  uint64_t prev = ticket < st->capacity ? 0 : ticket - st->capacity + 1;
  for (int spins = 0;
       __atomic_load_n(st->seq + slot, __ATOMIC_ACQUIRE) != prev;) {
    if (++spins > 128) sched_yield();
  }
  // seq 0 marks the slot in-progress; a reader that loaded the old stamp
  // and races our word stores fails its recheck
  __atomic_store_n(st->seq + slot, 0, __ATOMIC_RELEASE);
  uint64_t w1 = (uint64_t)code | ((uint64_t)tag << 16) |
                ((uint64_t)(uint32_t)(unsigned long)pthread_self() << 32);
  __atomic_store_n(r + 0, now_ns(), __ATOMIC_RELAXED);
  __atomic_store_n(r + 1, w1, __ATOMIC_RELAXED);
  __atomic_store_n(r + 2, (uint64_t)a1, __ATOMIC_RELAXED);
  __atomic_store_n(r + 3, (uint64_t)a2, __ATOMIC_RELAXED);
  __atomic_store_n(st->seq + slot, ticket + 1, __ATOMIC_RELEASE);
  __atomic_fetch_add(st->metrics + kMetEmitted, 1, __ATOMIC_RELAXED);
}

void metric_add(MetricIdx i, uint64_t n) {
  State *st = state();
  if (!st) return;
  __atomic_fetch_add(st->metrics + i, n, __ATOMIC_RELAXED);
}

void metric_store(MetricIdx i, uint64_t v) {
  State *st = state();
  if (!st) return;
  __atomic_store_n(st->metrics + i, v, __ATOMIC_RELAXED);
}

uint64_t metric_get(MetricIdx i) {
  State *st = state();
  if (!st) return 0;
  return __atomic_load_n(st->metrics + i, __ATOMIC_RELAXED);
}

}  // namespace tpr_obs

// -- C ABI -------------------------------------------------------------------

using tpr_obs::State;

extern "C" {

int tpr_obs_enabled(void) { return tpr_obs::enabled() ? 1 : 0; }

const char *tpr_obs_shm_name(void) {
  State *st = tpr_obs::state();
  return st ? st->shm.name.c_str() : "";
}

uint32_t tpr_obs_layout_version(void) { return tpr_obs::kObsVersion; }

uint32_t tpr_obs_capacity(void) {
  State *st = tpr_obs::state();
  return st ? st->capacity : 0;
}

void tpr_obs_counters(uint64_t *out, int n) {
  State *st = tpr_obs::state();
  for (int i = 0; i < n && i < (int)tpr_obs::kNumMetrics; i++)
    out[i] = st ? __atomic_load_n(st->metrics + i, __ATOMIC_RELAXED) : 0;
}

int tpr_obs_read(uint8_t *out, int max_records) {
  State *st = tpr_obs::state();
  if (!st || !out || max_records <= 0) return 0;
  int n = 0;
  for (uint32_t slot = 0; slot < st->capacity && n < max_records; slot++) {
    uint64_t s1 = __atomic_load_n(st->seq + slot, __ATOMIC_ACQUIRE);
    if (s1 == 0) continue;
    uint64_t w[4];
    const uint64_t *r = st->recs + (size_t)slot * 4;
    for (int k = 0; k < 4; k++)
      w[k] = __atomic_load_n(r + k, __ATOMIC_RELAXED);
    // acquire recheck: pairs with the writer's closing release store, so
    // a stable stamp proves the four word loads saw one whole record
    uint64_t s2 = __atomic_load_n(st->seq + slot, __ATOMIC_ACQUIRE);
    if (s2 != s1) continue;  // torn: a writer wrapped onto this slot
    memcpy(out + (size_t)n * tpr_obs::kRecordBytes, w, sizeof w);
    n++;
  }
  return n;
}

int tpr_obs_tag_name(uint32_t tag, char *out, int cap) {
  State *st = tpr_obs::state();
  if (!st || !out || cap <= 0 || tag == 0 ||
      tag > tpr_obs::kTagCap)
    return 0;
  uint32_t n = __atomic_load_n(st->tag_count, __ATOMIC_ACQUIRE);
  if (tag > n) return 0;
  uint8_t *slot = st->tags + (size_t)(tag - 1) * tpr_obs::kTagBytes;
  uint16_t slen;
  memcpy(&slen, slot, 2);
  int w = slen < cap - 1 ? slen : cap - 1;
  memcpy(out, slot + 2, w);
  out[w] = '\0';
  return w;
}

uint16_t tpr_obs_tag_for(const char *name) { return tpr_obs::tag_for(name); }

void tpr_obs_emit(uint16_t code, uint16_t tag, int64_t a1, int64_t a2) {
  tpr_obs::emit(code, tag, a1, a2);
}

void tpr_obs_reset(void) {
  State *st = tpr_obs::state();
  if (!st) return;
  // test isolation only — callers quiesce emitters first (the Python
  // flight recorder's reset() makes the same promise)
  std::lock_guard<std::mutex> lk(tpr_obs::g_init_mu);
  for (uint32_t i = 0; i < st->capacity; i++)
    __atomic_store_n(st->seq + i, 0, __ATOMIC_RELAXED);
  for (int i = 0; i < (int)tpr_obs::kNumMetrics; i++)
    __atomic_store_n(st->metrics + i, 0, __ATOMIC_RELAXED);
  // The tag table must reset too: a long-lived process interning a fresh
  // nconn:/nctrl:/nrdv: set per connection would exhaust the kTagCap slots
  // across many reset() generations and every later entity would collapse
  // into the anonymous tag.
  memset(st->tags, 0, (size_t)tpr_obs::kTagCap * tpr_obs::kTagBytes);
  __atomic_store_n(st->tag_count, 0u, __ATOMIC_RELAXED);
  __atomic_store_n(st->ticket, 0, __ATOMIC_RELEASE);
}

void tpr_obs_postfork(void) {
  std::lock_guard<std::mutex> lk(tpr_obs::g_init_mu);
  State *old = tpr_obs::g_state;
  if (old) {
    // the region belongs to the parent: unmap, never unlink
    old->shm.owner = false;
    old->shm.close();
    delete old;
  }
  State *fresh = nullptr;
  if (!tpr_obs::env_off("TPURPC_NATIVE_OBS"))
    fresh = tpr_obs::build_state();
  __atomic_store_n(&tpr_obs::g_state, fresh, __ATOMIC_RELAXED);
  __atomic_store_n(&tpr_obs::g_init_done, true, __ATOMIC_RELEASE);
}

}  // extern "C"
