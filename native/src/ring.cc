// tpurpc native data plane: framed-ring hot ops behind a C ABI (ctypes-loaded).
//
// Same wire format as tpurpc/core/ring.py (which re-derives the math of the
// reference's src/core/lib/ibverbs/ring_buffer.{h,cc}, then diverges on
// completion detection):
//
//   [8B header = lo32 payload len | hi32 seq32][payload, padded to 8B]
//   [8B footer = seq64 ^ kFooterSalt]
//
// where seq is the per-ring monotone message counter (seq32 = its low 32
// bits). The reference detects completion by keeping the consumed region
// zero (reader memsets what it eats, ring_buffer.cc:122-191); that is a
// full extra memory pass over every byte. Stamping each message with a
// never-repeating sequence makes stale bytes self-evidently stale instead:
// a message is complete iff header.seq32 == expected && footer == expected
// seq64 pattern — 96 bits of freshness, no zeroing. (The peer writes every
// ring byte either way; this is not a trust boundary.)
//
// capacity is a power of two >= 64; offsets are monotonically increasing
// 64-bit counters masked on access; no 8B word ever straddles the wrap.
//
// Memory model: one producer process writes, one consumer process reads over
// shared memory. Stores are ordered payload -> footer -> header with a
// release fence before the header store; the reader issues an acquire fence
// after observing a matching header+footer. (The reference gets placement
// order from a single RDMA WRITE; shm needs the fences spelled out.)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <sched.h>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define TPR_PAUSE() _mm_pause()
#else
#define TPR_PAUSE() std::atomic_thread_fence(std::memory_order_seq_cst)
#endif

namespace {

constexpr uint64_t kAlign = 8;
constexpr uint64_t kHeader = 8;
constexpr uint64_t kFooter = 8;
constexpr uint64_t kFooterSalt = 0xA5C3F00D5EEDFACEULL;
constexpr uint64_t kReserved = kHeader + kFooter + kAlign;

inline uint64_t footer_stamp(uint64_t seq) { return seq ^ kFooterSalt; }
inline uint64_t header_stamp(uint64_t len, uint64_t seq) {
  return (len & 0xFFFFFFFFULL) | (seq << 32);
}

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }
inline uint64_t msg_span(uint64_t len) { return kHeader + align_up(len) + kFooter; }

inline uint64_t load_word(const uint8_t* ring, uint64_t mask, uint64_t off) {
  uint64_t w;
  std::memcpy(&w, ring + (off & mask), sizeof(w));
  return w;
}

inline void store_word(uint8_t* ring, uint64_t mask, uint64_t off, uint64_t w) {
  std::memcpy(ring + (off & mask), &w, sizeof(w));
}

// Copy a logical span out of the ring (<=2 physical segments at the wrap).
void copy_out(const uint8_t* ring, uint64_t cap, uint64_t mask, uint64_t off,
              uint8_t* dst, uint64_t n) {
  uint64_t p = off & mask;
  uint64_t first = cap - p;
  if (n <= first) {
    std::memcpy(dst, ring + p, n);
  } else {
    std::memcpy(dst, ring + p, first);
    std::memcpy(dst + first, ring, n - first);
  }
}

void copy_in(uint8_t* ring, uint64_t cap, uint64_t mask, uint64_t off,
             const uint8_t* src, uint64_t n) {
  uint64_t p = off & mask;
  uint64_t first = cap - p;
  if (n <= first) {
    std::memcpy(ring + p, src, n);
  } else {
    std::memcpy(ring + p, src, first);
    std::memcpy(ring, src + first, n - first);
  }
}

// Complete-message scan at `off` for sequence number `seq`: payload length,
// 0 if none/incomplete. A seq32 match with an implausible length is treated
// as stale bytes, NOT corruption: after 2^32 messages the 32-bit stamp laps,
// and old payload bytes (e.g. zeros, whose hi-word matches any seq ≡ 0
// mod 2^32 — including the all-zero fresh ring at seq 0) may transiently
// mimic a stamped header until the writer's real header lands. The 64-bit
// footer stamp still gates actual completion.
uint64_t message_at(const uint8_t* ring, uint64_t cap, uint64_t mask,
                    uint64_t off, uint64_t seq) {
  uint64_t hdr = load_word(ring, mask, off);
  if ((hdr >> 32) != (seq & 0xFFFFFFFFULL)) return 0;  // stale or in-flight
  uint64_t len = hdr & 0xFFFFFFFFULL;
  if (len == 0 || len > cap - kReserved) return 0;  // stale lookalike
  uint64_t footer = load_word(ring, mask, off + kHeader + align_up(len));
  if (footer != footer_stamp(seq)) return 0;  // body still in flight
  std::atomic_thread_fence(std::memory_order_acquire);
  return len;
}

}  // namespace

extern "C" {

int tpr_abi_version() { return 7; }

// --- waiter-advertisement protocol (the futex-style sleep handshake) --------
//
// The reference's BP mode costs ZERO syscalls per send: the receiver discovers
// data by polling the ring, and only the EVENT/BPEV sleep path needs a wake
// (write-with-imm / completion channel, rdma_event_posix.cc). Our analog: a
// waiter publishes "I am blocked on the notify fd" in its own status region
// before sleeping; the peer reads that word after its data/credit store and
// sends the 1-byte notify ONLY when someone is actually asleep.
//
// Correctness is the classic Dekker/futex argument and needs StoreLoad
// ordering on both sides, which x86's TSO does NOT give for free:
//   waiter:  store waiting=1 (seq_cst = full fence) ; load ring state
//   sender:  store data      ; full fence           ; load waiting
// If the waiter missed the data, its waiting store is ordered before the
// sender's fenced load, so the sender sees waiting=1 and kicks. If the sender
// saw waiting=0, the waiter's store came later, so its ring re-check (after
// its own fence) sees the data and never blocks. Lost wakeups are impossible.

void tpr_store_u64_seqcst(uint8_t* addr, uint64_t val) {
  __atomic_store_n(reinterpret_cast<uint64_t*>(addr), val, __ATOMIC_SEQ_CST);
  // The waiter's subsequent ring/credit re-checks are PLAIN loads issued from
  // Python; a seq_cst store alone does not forbid them from hoisting above it
  // on aarch64 (stlr only orders against ldar). The explicit fence buys the
  // StoreLoad edge the proof needs on every architecture (x86: the xchg the
  // store compiles to was already a full barrier; the extra mfence is noise
  // on the sleep path).
  std::atomic_thread_fence(std::memory_order_seq_cst);
}

uint64_t tpr_load_u64_fenced(const uint8_t* addr) {
  std::atomic_thread_fence(std::memory_order_seq_cst);
  return __atomic_load_n(reinterpret_cast<const uint64_t*>(addr),
                         __ATOMIC_SEQ_CST);
}

// Total drainable payload bytes (all complete messages + pending remainder).
// `seq` is the expected sequence of the FIRST unparsed message at/after head.
uint64_t tpr_ring_readable(const uint8_t* ring, uint64_t cap, uint64_t head,
                           uint64_t msg_len, uint64_t msg_read,
                           uint64_t seq) {
  uint64_t mask = cap - 1;
  uint64_t total = 0;
  uint64_t off = head;
  if (msg_len) {  // in-progress message carries `seq`; the next one is seq+1
    total += msg_len - msg_read;
    off += msg_span(msg_len);
    ++seq;
  }
  uint64_t scanned = 0;
  while (scanned < cap) {
    uint64_t ln = message_at(ring, cap, mask, off, seq);
    if (ln == 0 || ln == ~0ULL) break;
    total += ln;
    uint64_t sp = msg_span(ln);
    off += sp;
    scanned += sp;
    ++seq;
  }
  return total;
}

// Drain up to dst_len payload bytes. Returns bytes read, or ~0 on corruption.
// head/msg_len/msg_read/consumed/seq are caller state, updated in place.
// No zeroing of consumed spans: freshness comes from the seq stamps.
uint64_t tpr_ring_read_into(uint8_t* ring, uint64_t cap, uint64_t* head,
                            uint64_t* msg_len, uint64_t* msg_read,
                            uint8_t* dst, uint64_t dst_len,
                            uint64_t* consumed, uint64_t* seq) {
  uint64_t mask = cap - 1;
  uint64_t total = 0;
  while (total < dst_len) {
    if (*msg_len == 0) {
      uint64_t ln = message_at(ring, cap, mask, *head, *seq);
      if (ln == ~0ULL) return ~0ULL;
      if (ln == 0) break;
      *msg_len = ln;
      *msg_read = 0;
    }
    uint64_t want = dst_len - total;
    uint64_t left = *msg_len - *msg_read;
    uint64_t n = want < left ? want : left;
    copy_out(ring, cap, mask, *head + kHeader + *msg_read, dst + total, n);
    *msg_read += n;
    total += n;
    if (*msg_read == *msg_len) {
      uint64_t sp = msg_span(*msg_len);
      *head += sp;
      *consumed += sp;
      *msg_len = 0;
      *msg_read = 0;
      ++*seq;
    }
  }
  return total;
}

// Gather-encode one message at *tail (payload -> footer -> fence -> header),
// stamped with *seq. Returns payload bytes written, or ~0 if it doesn't fit
// the writable span.
uint64_t tpr_ring_writev(uint8_t* ring, uint64_t cap, uint64_t* tail,
                         uint64_t remote_head,
                         const uint8_t* const* segs, const uint64_t* lens,
                         uint32_t nsegs, uint64_t* seq) {
  uint64_t mask = cap - 1;
  uint64_t payload = 0;
  for (uint32_t i = 0; i < nsegs; ++i) payload += lens[i];
  if (payload == 0) return 0;
  uint64_t used = *tail - remote_head;
  uint64_t writable = used + kReserved >= cap ? 0 : cap - used - kReserved;
  if (payload > writable) return ~0ULL;
  uint64_t off = *tail + kHeader;
  for (uint32_t i = 0; i < nsegs; ++i) {
    copy_in(ring, cap, mask, off, segs[i], lens[i]);
    off += lens[i];
  }
  store_word(ring, mask, *tail + kHeader + align_up(payload),
             footer_stamp(*seq));
  std::atomic_thread_fence(std::memory_order_release);
  store_word(ring, mask, *tail, header_stamp(payload, *seq));
  *tail += msg_span(payload);
  ++*seq;
  return payload;
}

// --- zero-copy send lease (VERDICT r4 next #6) ------------------------------
// The reference's SendZerocopy (pair.cc:793-941) posts the CALLER's pinned
// buffer to the NIC, so no CPU staging copy happens before the wire. A shm
// ring's analog: let the producer BUILD the payload directly in the peer
// ring — reserve one message's span, hand back its (<=2, wrap) physical
// segments, and publish only at commit. Between the two the reader cannot
// see the message (its header word still fails the seq check), so the
// producer may fill the span at leisure. Claims must be serialized by the
// caller (the channel's write lock) — reserve does not advance *tail;
// commit does, so two concurrent reserves would claim the same span.

// Largest payload one message can ever carry in a ring of `cap` bytes —
// the ONE home of the bound both reserve-side prechecks and this file's
// own math use (a drifted duplicate would make reserve_lease spin forever
// on a payload tpr_ring_reserve can never grant).
uint64_t tpr_ring_max_payload(uint64_t cap) {
  return cap > kReserved ? cap - kReserved : 0;
}

uint64_t tpr_ring_reserve(uint8_t* ring, uint64_t cap, uint64_t tail,
                          uint64_t remote_head, uint64_t payload_len,
                          uint8_t** p1, uint64_t* l1,
                          uint8_t** p2, uint64_t* l2) {
  uint64_t mask = cap - 1;
  if (payload_len == 0 || payload_len > cap - kReserved) return 0;
  uint64_t used = tail - remote_head;
  uint64_t writable = used + kReserved >= cap ? 0 : cap - used - kReserved;
  if (payload_len > writable) return 0;
  uint64_t p = (tail + kHeader) & mask;
  uint64_t first = cap - p;
  if (payload_len <= first) {
    *p1 = ring + p;
    *l1 = payload_len;
    *p2 = nullptr;
    *l2 = 0;
  } else {
    *p1 = ring + p;
    *l1 = first;
    *p2 = ring;
    *l2 = payload_len - first;
  }
  return 1;
}

void tpr_ring_commit(uint8_t* ring, uint64_t cap, uint64_t* tail,
                     uint64_t payload_len, uint64_t* seq) {
  uint64_t mask = cap - 1;
  store_word(ring, mask, *tail + kHeader + align_up(payload_len),
             footer_stamp(*seq));
  std::atomic_thread_fence(std::memory_order_release);
  store_word(ring, mask, *tail, header_stamp(payload_len, *seq));
  *tail += msg_span(payload_len);
  ++*seq;
}

// Fused fast-path send (the per-RPC hot loop of pair.py's send(), one call
// instead of ~10 Python-level steps): fold the peer-published credit head
// from our status page, gather-encode the segments as chunked ring messages
// under the credit budget, then decide — with the fenced load the sleep
// protocol requires — whether the peer needs a notify byte.
//
//   status_addr:      our status page (peer one-sided-writes credits at +0)
//   peer_rxwait_addr: peer's status page read-waiter word, or null (then
//                     *notify_out is always 1 when bytes were written)
//   chunk_size:       max payload per ring message (send_chunk_size)
//
// Returns payload bytes accepted — possibly a PARTIAL total (0 = fully
// stalled for credits); the caller resumes the remainder via its byte
// cursor. *tail / *seq / *remote_head update in place. Never returns ~0ULL.
uint64_t tpr_send_fast(uint8_t* ring, uint64_t cap, uint64_t* tail,
                       uint64_t* seq, const uint8_t* status_addr,
                       uint64_t* remote_head,
                       const uint8_t* peer_rxwait_addr,
                       const uint8_t* const* segs, const uint64_t* lens,
                       uint32_t nsegs, uint64_t chunk_size,
                       int* notify_out) {
  // fold credits (pair.cc:294-301 reading mirrored remote_head; monotone)
  uint64_t head = __atomic_load_n(
      reinterpret_cast<const uint64_t*>(status_addr), __ATOMIC_ACQUIRE);
  if (head > *remote_head && head <= *tail) *remote_head = head;

  uint64_t total = 0;
  uint32_t si = 0;
  uint64_t so = 0;
  const uint8_t* chunk_ptrs[64];
  uint64_t chunk_lens[64];
  while (si < nsegs) {
    uint64_t used = *tail - *remote_head;
    uint64_t writable = used + kReserved >= cap ? 0 : cap - used - kReserved;
    uint64_t budget = writable < chunk_size ? writable : chunk_size;
    if (budget == 0) break;
    // assemble one chunk's worth of (sub)segments
    uint32_t n = 0;
    uint64_t take_total = 0;
    while (si < nsegs && take_total < budget && n < 64) {
      uint64_t avail = lens[si] - so;
      uint64_t take = budget - take_total < avail ? budget - take_total : avail;
      if (take) {
        chunk_ptrs[n] = segs[si] + so;
        chunk_lens[n] = take;
        ++n;
      }
      take_total += take;
      so += take;
      if (so == lens[si]) {
        ++si;
        so = 0;
      }
    }
    if (take_total == 0) break;
    uint64_t got = tpr_ring_writev(ring, cap, tail, *remote_head,
                                   chunk_ptrs, chunk_lens, n, seq);
    if (got == ~0ULL) break;  // unreachable (budget uses writev's own math);
                              // defensively: report what IS on the wire —
                              // the caller resumes from the returned total
    total += got;
  }
  // Notify only a sleeping peer (fenced load AFTER the data stores — the
  // producer half of the sleep protocol; see tpr_store_u64_seqcst).
  if (total == 0) {
    *notify_out = 0;
  } else if (peer_rxwait_addr == nullptr) {
    *notify_out = 1;
  } else {
    std::atomic_thread_fence(std::memory_order_seq_cst);
    *notify_out = __atomic_load_n(
        reinterpret_cast<const uint64_t*>(peer_rxwait_addr),
        __ATOMIC_SEQ_CST) != 0;
  }
  return total;
}

// Has a complete message? (poller fast check; 1 = yes, 0 = no, -1 corruption)
int tpr_ring_has_message(const uint8_t* ring, uint64_t cap, uint64_t head,
                         uint64_t msg_len, uint64_t seq) {
  if (msg_len) return 1;
  uint64_t ln = message_at(ring, cap, cap - 1, head, seq);
  if (ln == ~0ULL) return -1;
  return ln != 0 ? 1 : 0;
}

namespace {
inline uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ULL + uint64_t(ts.tv_nsec);
}
}  // namespace

// GIL-free spin-waits (loaded via CDLL, not PyDLL): the busy window of the
// BP/BPEV disciplines runs here at native speed without starving other
// Python threads. Mirrors the reference's busy-poll loops
// (ev_epollex_rdma_bp_linux.cc:1020-1110 scanning pairs for HasMessage,
// pair.cc:407-411 waitDataWrites spinning the CQ). Callers bound each call
// by timeout_us and re-check full pair state between calls.

// Spin until a complete message sits at `head` (1), corruption (-1), or
// timeout (0). The watched words live in this side's OWN receive ring, whose
// lifetime the caller pins for the duration of the call.
int tpr_ring_wait_message(const uint8_t* ring, uint64_t cap, uint64_t head,
                          uint64_t seq, uint64_t timeout_us) {
  uint64_t mask = cap - 1;
  uint64_t deadline = now_ns() + timeout_us * 1000ULL;
  for (;;) {
    uint64_t ln = message_at(ring, cap, mask, head, seq);
    if (ln == ~0ULL) return -1;
    if (ln != 0) return 1;
    for (int i = 0; i < 64; ++i) TPR_PAUSE();
    // sched_yield per lap (GRPC_RDMA_POLLING_YIELD, rdma_utils.h:75-80):
    // ~100ns on an idle multicore; on an oversubscribed host it hands the
    // core to the producer we are waiting on instead of burning the slice.
    sched_yield();
    if (now_ns() >= deadline) return 0;
  }
}

// Spin until the u64 at `addr` differs from `old` (returns 1) or timeout (0).
// Used by credit-stalled writers watching their own status buffer's
// remote-head word (the peer one-sided-writes credits there), and for the
// peer_exit word.
int tpr_spin_u64_change(const uint8_t* addr, uint64_t old_val,
                        uint64_t timeout_us) {
  uint64_t deadline = now_ns() + timeout_us * 1000ULL;
  for (;;) {
    uint64_t w;
    std::memcpy(&w, addr, sizeof(w));
    if (w != old_val) {
      std::atomic_thread_fence(std::memory_order_acquire);
      return 1;
    }
    for (int i = 0; i < 64; ++i) TPR_PAUSE();
    sched_yield();
    if (now_ns() >= deadline) return 0;
  }
}

}  // extern "C"
