// tpurpc native data plane: framed-ring hot ops behind a C ABI (ctypes-loaded).
//
// Same wire format as tpurpc/core/ring.py (which re-derives the math of the
// reference's src/core/lib/ibverbs/ring_buffer.{h,cc}):
//
//   [8B header = payload len][payload, zero-padded to 8B][8B footer = ~0]
//
// capacity is a power of two >= 64; offsets are monotonically increasing
// 64-bit counters masked on access; no 8B word ever straddles the wrap.
//
// Memory model: one producer process writes, one consumer process reads over
// shared memory. Stores are ordered payload -> footer -> header with a
// release fence before the header store; the reader issues an acquire fence
// after observing header!=0 && footer==~0. (The reference gets placement
// order from a single RDMA WRITE; shm needs the fences spelled out.)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <sched.h>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define TPR_PAUSE() _mm_pause()
#else
#define TPR_PAUSE() std::atomic_thread_fence(std::memory_order_seq_cst)
#endif

namespace {

constexpr uint64_t kAlign = 8;
constexpr uint64_t kHeader = 8;
constexpr uint64_t kFooter = 8;
constexpr uint64_t kFooterMagic = ~0ULL;
constexpr uint64_t kReserved = kHeader + kFooter + kAlign;

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }
inline uint64_t msg_span(uint64_t len) { return kHeader + align_up(len) + kFooter; }

inline uint64_t load_word(const uint8_t* ring, uint64_t mask, uint64_t off) {
  uint64_t w;
  std::memcpy(&w, ring + (off & mask), sizeof(w));
  return w;
}

inline void store_word(uint8_t* ring, uint64_t mask, uint64_t off, uint64_t w) {
  std::memcpy(ring + (off & mask), &w, sizeof(w));
}

// Copy a logical span out of the ring (<=2 physical segments at the wrap).
void copy_out(const uint8_t* ring, uint64_t cap, uint64_t mask, uint64_t off,
              uint8_t* dst, uint64_t n) {
  uint64_t p = off & mask;
  uint64_t first = cap - p;
  if (n <= first) {
    std::memcpy(dst, ring + p, n);
  } else {
    std::memcpy(dst, ring + p, first);
    std::memcpy(dst + first, ring, n - first);
  }
}

void copy_in(uint8_t* ring, uint64_t cap, uint64_t mask, uint64_t off,
             const uint8_t* src, uint64_t n) {
  uint64_t p = off & mask;
  uint64_t first = cap - p;
  if (n <= first) {
    std::memcpy(ring + p, src, n);
  } else {
    std::memcpy(ring + p, src, first);
    std::memcpy(ring, src + first, n - first);
  }
}

void zero_span(uint8_t* ring, uint64_t cap, uint64_t mask, uint64_t off,
               uint64_t n) {
  uint64_t p = off & mask;
  uint64_t first = cap - p;
  if (n <= first) {
    std::memset(ring + p, 0, n);
  } else {
    std::memset(ring + p, 0, first);
    std::memset(ring, 0, n - first);
  }
}

// Complete-message scan at `off`: payload length, 0 if none/incomplete,
// ~0 on corruption (header exceeds max payload).
uint64_t message_at(const uint8_t* ring, uint64_t cap, uint64_t mask,
                    uint64_t off) {
  uint64_t hdr = load_word(ring, mask, off);
  if (hdr == 0) return 0;
  if (hdr > cap - kReserved) return ~0ULL;
  uint64_t footer = load_word(ring, mask, off + kHeader + align_up(hdr));
  if (footer != kFooterMagic) return 0;
  std::atomic_thread_fence(std::memory_order_acquire);
  return hdr;
}

}  // namespace

extern "C" {

int tpr_abi_version() { return 2; }

// Total drainable payload bytes (all complete messages + pending remainder).
uint64_t tpr_ring_readable(const uint8_t* ring, uint64_t cap, uint64_t head,
                           uint64_t msg_len, uint64_t msg_read) {
  uint64_t mask = cap - 1;
  uint64_t total = 0;
  uint64_t off = head;
  if (msg_len) {
    total += msg_len - msg_read;
    off += msg_span(msg_len);
  }
  uint64_t scanned = 0;
  while (scanned < cap) {
    uint64_t ln = message_at(ring, cap, mask, off);
    if (ln == 0 || ln == ~0ULL) break;
    total += ln;
    uint64_t sp = msg_span(ln);
    off += sp;
    scanned += sp;
  }
  return total;
}

// Drain up to dst_len payload bytes. Returns bytes read, or ~0 on corruption.
// head/msg_len/msg_read/consumed are caller state, updated in place.
uint64_t tpr_ring_read_into(uint8_t* ring, uint64_t cap, uint64_t* head,
                            uint64_t* msg_len, uint64_t* msg_read,
                            uint8_t* dst, uint64_t dst_len,
                            uint64_t* consumed) {
  uint64_t mask = cap - 1;
  uint64_t total = 0;
  while (total < dst_len) {
    if (*msg_len == 0) {
      uint64_t ln = message_at(ring, cap, mask, *head);
      if (ln == ~0ULL) return ~0ULL;
      if (ln == 0) break;
      *msg_len = ln;
      *msg_read = 0;
    }
    uint64_t want = dst_len - total;
    uint64_t left = *msg_len - *msg_read;
    uint64_t n = want < left ? want : left;
    copy_out(ring, cap, mask, *head + kHeader + *msg_read, dst + total, n);
    *msg_read += n;
    total += n;
    if (*msg_read == *msg_len) {
      uint64_t sp = msg_span(*msg_len);
      zero_span(ring, cap, mask, *head, sp);
      *head += sp;
      *consumed += sp;
      *msg_len = 0;
      *msg_read = 0;
    }
  }
  return total;
}

// Gather-encode one message at *tail (payload -> footer -> fence -> header).
// Returns payload bytes written, or ~0 if it doesn't fit the writable span.
uint64_t tpr_ring_writev(uint8_t* ring, uint64_t cap, uint64_t* tail,
                         uint64_t remote_head,
                         const uint8_t* const* segs, const uint64_t* lens,
                         uint32_t nsegs) {
  uint64_t mask = cap - 1;
  uint64_t payload = 0;
  for (uint32_t i = 0; i < nsegs; ++i) payload += lens[i];
  if (payload == 0) return 0;
  uint64_t used = *tail - remote_head;
  uint64_t writable = used + kReserved >= cap ? 0 : cap - used - kReserved;
  if (payload > writable) return ~0ULL;
  uint64_t off = *tail + kHeader;
  for (uint32_t i = 0; i < nsegs; ++i) {
    copy_in(ring, cap, mask, off, segs[i], lens[i]);
    off += lens[i];
  }
  store_word(ring, mask, *tail + kHeader + align_up(payload), kFooterMagic);
  std::atomic_thread_fence(std::memory_order_release);
  store_word(ring, mask, *tail, payload);
  *tail += msg_span(payload);
  return payload;
}

// Has a complete message? (poller fast check; 1 = yes, 0 = no, -1 corruption)
int tpr_ring_has_message(const uint8_t* ring, uint64_t cap, uint64_t head,
                         uint64_t msg_len) {
  if (msg_len) return 1;
  uint64_t ln = message_at(ring, cap, cap - 1, head);
  if (ln == ~0ULL) return -1;
  return ln != 0 ? 1 : 0;
}

namespace {
inline uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ULL + uint64_t(ts.tv_nsec);
}
}  // namespace

// GIL-free spin-waits (loaded via CDLL, not PyDLL): the busy window of the
// BP/BPEV disciplines runs here at native speed without starving other
// Python threads. Mirrors the reference's busy-poll loops
// (ev_epollex_rdma_bp_linux.cc:1020-1110 scanning pairs for HasMessage,
// pair.cc:407-411 waitDataWrites spinning the CQ). Callers bound each call
// by timeout_us and re-check full pair state between calls.

// Spin until a complete message sits at `head` (1), corruption (-1), or
// timeout (0). The watched words live in this side's OWN receive ring, whose
// lifetime the caller pins for the duration of the call.
int tpr_ring_wait_message(const uint8_t* ring, uint64_t cap, uint64_t head,
                          uint64_t timeout_us) {
  uint64_t mask = cap - 1;
  uint64_t deadline = now_ns() + timeout_us * 1000ULL;
  for (;;) {
    uint64_t ln = message_at(ring, cap, mask, head);
    if (ln == ~0ULL) return -1;
    if (ln != 0) return 1;
    for (int i = 0; i < 64; ++i) TPR_PAUSE();
    // sched_yield per lap (GRPC_RDMA_POLLING_YIELD, rdma_utils.h:75-80):
    // ~100ns on an idle multicore; on an oversubscribed host it hands the
    // core to the producer we are waiting on instead of burning the slice.
    sched_yield();
    if (now_ns() >= deadline) return 0;
  }
}

// Spin until the u64 at `addr` differs from `old` (returns 1) or timeout (0).
// Used by credit-stalled writers watching their own status buffer's
// remote-head word (the peer one-sided-writes credits there), and for the
// peer_exit word.
int tpr_spin_u64_change(const uint8_t* addr, uint64_t old_val,
                        uint64_t timeout_us) {
  uint64_t deadline = now_ns() + timeout_us * 1000ULL;
  for (;;) {
    uint64_t w;
    std::memcpy(&w, addr, sizeof(w));
    if (w != old_val) {
      std::atomic_thread_fence(std::memory_order_acquire);
      return 1;
    }
    for (int i = 0; i < 64; ++i) TPR_PAUSE();
    sched_yield();
    if (now_ns() >= deadline) return 0;
  }
}

}  // extern "C"
