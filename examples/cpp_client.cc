// C++ application example: call a tpurpc server from native code.
//
// Mirrors the reference's C++ helloworld client (examples/cpp/helloworld)
// over tpurpc's app API (native/include/tpurpc/client.hpp). Works against
// any tpurpc server port — TCP, ring-platform, or TPU-platform listeners
// all protocol-sniff the native framing preface.
//
// Build (from the repo root; the test suite does this automatically):
//   g++ -std=c++17 -O2 examples/cpp_client.cc native/src/tpurpc_client.cc \
//       native/src/ring.cc -Inative/include -lpthread -o /tmp/tpurpc_cpp_client
// Set GRPC_PLATFORM_TYPE=RDMA_BP (or BPEV/EVENT) to ride the shm ring data
// plane — the app code is unchanged; only the byte pipe under it swaps.
// Run: /tmp/tpurpc_cpp_client <port>
//
// Exercises all the API surface a port of a reference C++ app needs:
// unary, server-streaming reads, client-streaming writes, deadline, ping.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "tpurpc/client.hpp"

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <port>\n", argv[0]);
    return 2;
  }
  int port = atoi(argv[1]);
  tpurpc::Channel ch("127.0.0.1", port);

  // liveness probe (reference analog: rate-limited QP query, pair.cc:349)
  printf("ping_us=%lld\n", static_cast<long long>(ch.PingUs()));

  // unary
  auto [st, reply] = ch.UnaryCall("/demo.Greeter/SayHello", "cpp", 5000);
  if (!st.ok()) {
    fprintf(stderr, "unary failed: %d %s\n", st.code, st.details.c_str());
    return 1;
  }
  printf("unary=%s\n", reply.c_str());

  // unary against a missing method: status must propagate
  auto [st2, _] = ch.UnaryCall("/no.Such/Method", "x", 5000);
  printf("missing_status=%d\n", st2.code);

  // bidi streaming echo
  tpurpc::ClientCall call = ch.StartCall("/demo.Greeter/Chat", {}, 10000);
  for (int i = 0; i < 3; i++) call.Write("m" + std::to_string(i));
  call.WritesDone();
  std::string msg;
  int got = 0;
  while (call.Read(&msg)) {
    printf("stream=%s\n", msg.c_str());
    got++;
  }
  tpurpc::Status fin = call.Finish();
  printf("stream_status=%d got=%d\n", fin.code, got);

  // large payload round trip (fragmentation across the 1 MiB frame bound)
  std::string big(3u << 20, 'A');
  auto [st3, echoed] = ch.UnaryCall("/demo.Greeter/Echo", big, 30000);
  printf("big_ok=%d len=%zu match=%d\n", st3.ok(), echoed.size(),
         echoed == big);

  return (st.ok() && st2.code == TPR_UNIMPLEMENTED && fin.ok() && got == 3 &&
          st3.ok() && echoed == big)
             ? 0
             : 1;
}
