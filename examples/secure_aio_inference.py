"""Secure async inference: TLS + asyncio + device-mode tensor service, in one.

Everything round-2 added, composed: the server runs async handlers behind a
TLS port (self-signed for the demo); the client awaits concurrent calls.
Platform comes from GRPC_PLATFORM_TYPE exactly as everywhere else — on the
ring platforms the TLS socket carries bootstrap + notify while payload rides
shm; on RDMA_TPU, device=True tensor methods decode into the HBM ring.

    python examples/secure_aio_inference.py
"""

from __future__ import annotations

import asyncio
import datetime
import ipaddress
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def self_signed() -> tuple:
    """Demo CA+cert for 127.0.0.1 (cryptography lib, in-memory only)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder().subject_name(name).issuer_name(name)
            .public_key(key.public_key()).serial_number(1)
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
                critical=False)
            .sign(key, hashes.SHA256()))
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    return key_pem, cert.public_bytes(serialization.Encoding.PEM)


async def main() -> int:
    import numpy as np

    import tpurpc.rpc as tps
    from tpurpc.jaxshim import codec
    from tpurpc.rpc import aio

    key_pem, cert_pem = self_signed()

    async def infer(raw, ctx):
        tree = codec.decode_tree(raw)
        await asyncio.sleep(0)  # stand-in for awaiting device work
        x = np.asarray(tree["x"])
        return codec.encode_tree_bytes({"mean": np.float32(x.mean()),
                                        "shape": np.asarray(x.shape)})

    srv = aio.Server(max_workers=8)
    srv.add_method("/demo.Model/Infer",
                   aio.unary_unary_rpc_method_handler(infer))
    port = srv.add_secure_port(
        "127.0.0.1:0", tps.ssl_server_credentials([(key_pem, cert_pem)]))
    await srv.start()

    creds = tps.ssl_channel_credentials(root_certificates=cert_pem)
    async with aio.Channel(f"localhost:{port}", credentials=creds) as ch:
        call = ch.unary_unary("/demo.Model/Infer")

        async def one(i: int):
            req = codec.encode_tree_bytes(
                {"x": np.full((4, 4), float(i), np.float32)})
            reply = codec.decode_tree(await call(req, timeout=30))
            return float(np.asarray(reply["mean"]).ravel()[0])

        means = await asyncio.gather(*[one(i) for i in range(4)])
    await srv.stop()
    assert means == [0.0, 1.0, 2.0, 3.0], means
    print("secure aio inference ok:", means)
    return 0


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
