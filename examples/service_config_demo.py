"""Service config demo: the resolver delivers per-method retry/timeout.

The gRPC shape (``service_config.cc`` / ``retry_service_config.cc`` /
``retry_throttle.cc``; tpurpc: ``tpurpc/rpc/service_config.py``): name
resolution returns addresses AND a JSON config; the channel applies
per-method timeouts and retry policies with ZERO call-site involvement —
operations tune retry behavior by changing what the control plane serves,
never by redeploying clients. Run it:

    python examples/service_config_demo.py

It stands up a deliberately flaky backend (fails twice, then answers), a
resolver that attaches a retryPolicy for exactly one method, and shows:
the configured method retries transparently; an unconfigured method
surfaces the failure; a control-plane config update re-tunes a LIVE
channel; the config's timeout caps a slow method.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tpurpc.rpc as rpc  # noqa: E402
from tpurpc.rpc.resolver import Resolution, register_resolver  # noqa: E402

CONFIG = {
    "methodConfig": [{
        "name": [{"service": "demo.Svc", "method": "Flaky"}],
        "retryPolicy": {"maxAttempts": 4, "initialBackoff": "0.02s",
                        "maxBackoff": "0.2s", "backoffMultiplier": 2,
                        "retryableStatusCodes": ["UNAVAILABLE"]},
    }, {
        "name": [{"service": "demo.Svc", "method": "Slow"}],
        "timeout": "0.3s",
    }],
    "retryThrottling": {"maxTokens": 10, "tokenRatio": 0.5},
}


class Flaky:
    def __init__(self, fail: int):
        self.fail, self.calls = fail, 0
        self.lock = threading.Lock()

    def __call__(self, req, ctx):
        with self.lock:
            self.calls += 1
            n = self.calls
        if n <= self.fail:
            ctx.abort(rpc.StatusCode.UNAVAILABLE, f"flaky (attempt {n})")
        return b"ok after %d attempts" % n


def main() -> None:
    flaky = Flaky(fail=2)
    srv = rpc.Server(max_workers=4)
    srv.add_method("/demo.Svc/Flaky",
                   rpc.unary_unary_rpc_method_handler(flaky))
    srv.add_method("/demo.Svc/NoRetry",
                   rpc.unary_unary_rpc_method_handler(Flaky(fail=10)))

    def slow(req, ctx):
        time.sleep(5)
        return b"too late"

    srv.add_method("/demo.Svc/Slow", rpc.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()

    # the resolver attaches the config to its result (gRPC's resolver
    # contract; a stock target string keeps working without one)
    register_resolver("democfg",
                      lambda rest: Resolution([("127.0.0.1", port)], CONFIG))

    with rpc.Channel("democfg:///demo") as ch:
        print("configured method retries transparently:")
        out = ch.unary_unary("/demo.Svc/Flaky")(b"", timeout=10)
        print("  ", out.decode(), "(server saw", flaky.calls, "attempts)")

        print("unconfigured method fails fast (retries are opt-in config):")
        try:
            ch.unary_unary("/demo.Svc/NoRetry")(b"", timeout=10)
        except rpc.RpcError as exc:
            print("  ", exc.code().name, "-", exc.details())

        print("config timeout caps a slow method (no call-site timeout):")
        t0 = time.monotonic()
        try:
            ch.unary_unary("/demo.Svc/Slow")(b"")
        except rpc.RpcError as exc:
            print(f"   {exc.code().name} after "
                  f"{time.monotonic() - t0:.2f}s (config says 0.3s)")

        print("live update widens Flaky's budget without touching calls:")
        wider = {"methodConfig": [{
            "name": [{"service": "demo.Svc"}],  # service-wide now
            "retryPolicy": {"maxAttempts": 5, "initialBackoff": "0.02s",
                            "maxBackoff": "0.2s", "backoffMultiplier": 2,
                            "retryableStatusCodes": ["UNAVAILABLE"]}}]}
        ch.update_service_config(wider)  # what a resolver refresh does
        out = ch.unary_unary("/demo.Svc/Flaky")(b"", timeout=10)
        print("  ", out.decode())

    srv.stop(grace=0)
    print("done")


if __name__ == "__main__":
    main()
