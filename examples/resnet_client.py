"""Client for the ResNet-50 inference server (BASELINE.json config #5)."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", default="127.0.0.1:50051")
    ap.add_argument("--n", type=int, default=4, help="requests to send")
    ap.add_argument("--image-size", type=int, default=224)
    args = ap.parse_args()

    import tpurpc.rpc as rpc
    from tpurpc.jaxshim import TensorClient

    rng = np.random.default_rng(0)
    with rpc.Channel(args.target) as ch:
        cli = TensorClient(ch)
        for i in range(args.n):
            images = rng.standard_normal(
                (1, args.image_size, args.image_size, 3)).astype(np.float32)
            t0 = time.perf_counter()
            out = cli.call("Classify", {"images": images}, timeout=120)
            dt = (time.perf_counter() - t0) * 1e3
            print(f"request {i}: top1={np.asarray(out['top1'])[0]} "
                  f"({dt:.1f} ms)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
