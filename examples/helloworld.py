"""Greeter over tpurpc — the reference's examples/cpp/helloworld analog.

Runs client and server in one process; also callable from a stock grpcio
client (same port, h2 sniffed).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tpurpc.rpc as rpc  # noqa: E402


def main() -> int:
    srv = rpc.Server(max_workers=4)
    srv.add_method(
        "/helloworld.Greeter/SayHello",
        rpc.unary_unary_rpc_method_handler(
            lambda name, ctx: b"Hello, " + bytes(name) + b"!"))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    with rpc.Channel(f"127.0.0.1:{port}") as ch:
        reply = ch.unary_unary("/helloworld.Greeter/SayHello")(b"tpu",
                                                               timeout=10)
        print(bytes(reply).decode())
    srv.stop(grace=0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
