"""Look-aside load balancing demo: blue/green traffic shifting.

The grpclb capability (``tpurpc/rpc/lookaside.py``): a balancer service
streams server lists; channels apply them live. Run it:

    python examples/lookaside_demo.py

It stands up two backends ("blue", "green"), a balancer, and a client
channel; directs all traffic to blue; then rebalances to green mid-flight
— the channel keeps serving throughout (kept backends keep their
connections; a call racing the swap retries per the normal UNAVAILABLE
path).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tpurpc.rpc as rpc  # noqa: E402


def backend(name: str):
    srv = rpc.Server(max_workers=4)
    srv.add_method(
        "/demo.Color/Which",
        rpc.unary_unary_rpc_method_handler(
            lambda req, ctx, n=name: n.encode(), inline=True))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def main() -> int:
    blue, blue_port = backend("blue")
    green, green_port = backend("green")

    bal_srv = rpc.Server(max_workers=4)
    balancer = rpc.LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("color", [f"127.0.0.1:{blue_port}"])

    # the channel's own target doubles as the fallback list
    ch = rpc.Channel(f"127.0.0.1:{blue_port}")
    watcher = rpc.enable_lookaside(ch, f"127.0.0.1:{bal_port}", "color")
    which = ch.unary_unary("/demo.Color/Which")

    def sample(n=20, timeout_s=15.0):
        votes = {}
        deadline = time.monotonic() + timeout_s
        while sum(votes.values()) < n and time.monotonic() < deadline:
            try:
                who = bytes(which(b"", timeout=5)).decode()
                votes[who] = votes.get(who, 0) + 1
            except rpc.RpcError:
                time.sleep(0.05)  # racing a swap: retry
        return votes

    v1 = sample()
    print("balancer -> blue:", v1)
    assert set(v1) == {"blue"}, v1

    balancer.set_servers("color", [f"127.0.0.1:{green_port}"])
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            if bytes(which(b"", timeout=5)) == b"green":
                break
        except rpc.RpcError:
            pass
        time.sleep(0.05)
    v2 = sample()
    print("rebalanced -> green:", v2)
    assert set(v2) == {"green"}, v2

    print("OK: live blue->green shift, no restart, no dropped channel")
    watcher.stop()
    ch.close()
    for s in (blue, green, bal_srv):
        s.stop(grace=0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
