"""ResNet-50 inference server (BASELINE.json config #5).

Image tensors arrive over the RPC plane (any transport — TCP, shm rings, or
stock gRPC clients via the h2 path), are decoded zero-copy, batched across
connections by the fan-in batcher, and classified by a jitted flax ResNet-50.

    python examples/resnet_server.py --port 50051 [--thin] [--batch 8]
    python examples/resnet_client.py --target 127.0.0.1:50051 --n 4
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_server(port: int = 0, thin: bool = False, batch: int = 8,
                 max_delay_s: float = 0.003):
    import jax
    import jax.numpy as jnp

    from tpurpc.jaxshim import serve_jax
    from tpurpc.models.resnet import (init_resnet, make_infer_fn,
                                      resnet18_thin, resnet50)

    size = 32 if thin else 224
    model = resnet18_thin(10) if thin else resnet50(1000)
    variables = init_resnet(jax.random.PRNGKey(0), model, image_size=size,
                            batch=1)
    infer = jax.jit(make_infer_fn(model))

    def handler(tree):
        logits = infer(variables, jnp.asarray(tree["images"]))
        return {"logits": logits,
                "top1": jnp.argmax(logits, axis=-1)}

    srv, bound, batcher = serve_jax(
        handler, f"0.0.0.0:{port}", name="Classify", batching=True,
        max_batch=batch, max_delay_s=max_delay_s)
    return srv, bound, batcher, size


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=50051)
    ap.add_argument("--thin", action="store_true",
                    help="small model/images for smoke runs")
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()
    srv, port, _, size = build_server(args.port, args.thin, args.batch)
    print(f"ResNet server on :{port} (image size {size})", flush=True)
    srv.wait_for_termination()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
