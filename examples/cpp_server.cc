// C++ application example: a native tpurpc server (no Python anywhere).
//
// Mirrors the reference's C++ helloworld server (examples/cpp/helloworld
// greeter_server) over tpurpc's native server API
// (native/include/tpurpc/server.hpp). Python tpurpc channels — and the C++
// client — call it over the native framing.
//
// Build: g++ -std=c++17 -O2 examples/cpp_server.cc \
//            native/src/tpurpc_server.cc native/src/ring.cc -Inative/include -lpthread \
//            -o /tmp/tpurpc_cpp_server
// Run: /tmp/tpurpc_cpp_server   (prints "PORT <n>", serves until stdin EOF)

#include <cstdio>
#include <string>

#include "tpurpc/server.hpp"

int main() {
  tpurpc::Server srv(0);

  srv.AddMethod("/demo.Greeter/SayHello", [](tpurpc::ServerCall &call) {
    std::string req;
    if (!call.Read(&req)) return 13;  // INTERNAL: no request
    call.Write("Hello, " + req + "!");
    return 0;
  });

  srv.AddMethod("/demo.Greeter/Echo", [](tpurpc::ServerCall &call) {
    std::string req;
    while (call.Read(&req)) call.Write(req);
    return call.cancelled() ? 1 : 0;
  });

  srv.AddMethod("/demo.Greeter/Chat", [](tpurpc::ServerCall &call) {
    std::string msg;
    while (call.Read(&msg)) call.Write("echo:" + msg);
    return call.cancelled() ? 1 : 0;
  });

  srv.Start();
  printf("PORT %d\n", srv.port());
  fflush(stdout);

  // serve until stdin closes (the test harness's lifetime signal)
  char buf[64];
  while (fgets(buf, sizeof buf, stdin) != nullptr) {
  }
  return 0;
}
