"""xDS-lite demo: bootstrap-discovered backends with live EDS updates.

The xds capability (``tpurpc/rpc/xds.py``, the reference's resolver/xds +
lb_policy/xds analog): a control plane publishes per-service endpoint
assignments; channels resolve ``xds:///service`` targets through the
gRPC bootstrap contract and track assignment changes live. Run it:

    python examples/xds_demo.py

It stands up two backends ("v1", "v2"), an ADS-lite control plane, and
an ``xds:///demo-svc`` channel; serves from v1; then publishes a new
assignment mid-flight — traffic moves to v2 without touching the client.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tpurpc.rpc as rpc  # noqa: E402
from tpurpc.rpc.xds import XdsServicer, xds_channel  # noqa: E402


def backend(version: str):
    srv = rpc.Server(max_workers=4)
    srv.add_method(
        "/demo.Svc/Version",
        rpc.unary_unary_rpc_method_handler(
            lambda req, ctx, v=version: v.encode(), inline=True))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def main() -> None:
    b1, p1 = backend("v1")
    b2, p2 = backend("v2")

    # the control plane: any tpurpc server carrying the ADS-lite method
    xds = XdsServicer()
    cp = rpc.Server(max_workers=4)
    xds.attach(cp)
    cp_port = cp.add_insecure_port("127.0.0.1:0")
    cp.start()
    xds.set_endpoints("demo-svc", [f"127.0.0.1:{p1}"])

    # the gRPC bootstrap contract (a file via GRPC_XDS_BOOTSTRAP works
    # identically; inline keeps the demo self-contained)
    os.environ["GRPC_XDS_BOOTSTRAP_CONFIG"] = json.dumps(
        {"xds_servers": [{"server_uri": f"127.0.0.1:{cp_port}"}],
         "node": {"id": "demo-node"}})

    ch, watcher = xds_channel("xds:///demo-svc")
    try:
        who = ch.unary_unary("/demo.Svc/Version")
        print("assignment v1:", who(b"", timeout=10).decode())

        xds.set_endpoints("demo-svc", [f"127.0.0.1:{p2}"])  # the EDS update
        deadline = time.monotonic() + 10
        seen = ""
        while time.monotonic() < deadline and seen != "v2":
            try:
                seen = who(b"", timeout=10).decode()
            except rpc.RpcError:
                continue  # a call racing the swap; the next one re-dials
            time.sleep(0.05)
        print("assignment v2:", seen)
        assert seen == "v2", "EDS update did not move traffic"
        print("OK: traffic followed the control plane")
    finally:
        watcher.stop()
        ch.close()
        cp.stop(grace=0)
        b1.stop(grace=0)
        b2.stop(grace=0)


if __name__ == "__main__":
    main()
