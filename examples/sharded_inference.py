"""Sharded model serving: RPC fan-in feeding a pjit'd multi-chip model.

The full TPU-native story in one file — the piece the reference never had
(its parallelism is RPC-plane only, SURVEY.md §2.7):

* bytes arrive over the swappable transport (`GRPC_PLATFORM_TYPE`) into one
  host process;
* `FanInBatcher` stacks concurrent requests into one batch;
* the model is a MoE transformer jitted over a 5-axis `jax.sharding.Mesh`
  (dp/pp/sp/tp/ep) — XLA inserts the psum/ppermute/all_to_all collectives
  that ride ICI on real multi-chip hardware;
* logits return to each caller over its own connection.

Runs anywhere via the virtual CPU mesh (the same trick the driver's
dryrun_multichip uses); on a real TPU pod slice the identical program
scales because axis sizes are compile-time constants, not code paths.

    python examples/sharded_inference.py          # 8 virtual devices
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tpurpc.jaxshim import FanInBatcher, TensorClient, add_tensor_method  # noqa: E402
from tpurpc.models.transformer import (TransformerConfig, build_forward,  # noqa: E402
                                       init_params, shard_params)
from tpurpc.parallel.mesh import build_mesh, factor_mesh  # noqa: E402
from tpurpc.rpc.channel import Channel  # noqa: E402
from tpurpc.rpc.server import Server  # noqa: E402


def main() -> int:
    jax.config.update("jax_platforms", "cpu")
    sizes = factor_mesh(8)
    mesh = build_mesh(8, sizes=sizes)
    print(f"mesh axes: {sizes}")
    cfg = TransformerConfig(vocab=128, d_model=32, n_heads=2 * sizes["tp"],
                            head_dim=8, d_ff=64, n_layers=2 * sizes["pp"],
                            n_experts=max(2, sizes["ep"]), capacity_factor=4.0,
                            n_micro=2)
    params = shard_params(init_params(jax.random.PRNGKey(0), cfg), cfg, mesh)
    fwd = build_forward(cfg, mesh)

    B, S = 4 * sizes["dp"] * sizes["ep"], 16 * sizes["sp"]

    # Warm the pjit'd forward BEFORE serving: the sharded compile can take
    # tens of seconds on a loaded single-core host, and production servers
    # never pay cold compiles inside a caller's RPC deadline (bench.py's
    # server warms the same way before printing READY).
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready(),
        fwd(params, np.zeros((B, S), np.int32)))

    def serve(tree):
        logits = fwd(params, tree["tokens"].astype(np.int32))
        return {"logits": logits}

    # fixed_bucket: always pad to exactly max_batch=B rows — the pjit'd
    # forward admits exactly [B, S] (shardings bake the batch size in)
    batcher = FanInBatcher(serve, max_batch=B, max_delay_s=0.05,
                           pad_to_bucket=True, fixed_bucket=True)
    srv = Server(max_workers=2 * B)
    add_tensor_method(srv, "Generate", batcher)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    print(f"sharded server on :{port} — model over {len(mesh.devices.ravel())}"
          " devices")

    rng = np.random.default_rng(0)
    rows = [rng.integers(0, cfg.vocab, (1, S)).astype(np.int32)
            for _ in range(B)]
    outs = [None] * B

    def client(i):
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)
            r = cli.call("Generate", {"tokens": rows[i]}, timeout=120)
            outs[i] = np.asarray(r["logits"])

    threads = [threading.Thread(target=client, args=(i,)) for i in range(B)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert all(o is not None and o.shape == (1, S, cfg.vocab) for o in outs)

    # cross-check: the batched sharded forward == per-row results the
    # clients got (fan-in stacking didn't mix rows up)
    dense = np.asarray(fwd(params, np.concatenate(rows, axis=0)))
    for i in range(B):
        np.testing.assert_allclose(outs[i][0], dense[i], rtol=2e-4, atol=2e-4)
    print(f"OK: {B} concurrent clients, one sharded batch, "
          f"row-exact logits (batches={batcher.batches_run})")
    srv.stop(grace=0)
    batcher.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
