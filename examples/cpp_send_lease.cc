// Zero-copy send lease E2E (round 5): serialize payloads DIRECTLY into the
// transport ring via tpr_call_send_reserve/commit and have a live Python
// server verify every byte (length + sum checksum per message). Exercises
// unwrapped spans, a span that wraps the ring edge (odd sizes walk the
// tail across the 4MB boundary), interleaving with classic tpr_call_send
// on the same stream, and the misuse guards (double reserve, foreign
// commit) — driven by tests/test_cpp_api.py::test_cpp_send_lease_ring.
//
// Usage: cpp_send_lease <port>     (GRPC_PLATFORM_TYPE=RDMA_BP|BPEV set
//                                   by the caller; lease needs the ring)

#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "tpurpc/client.h"

static uint64_t fill_pattern(uint8_t *dst, size_t len, uint64_t seed) {
  uint64_t sum = 0;
  for (size_t i = 0; i < len; ++i) {
    uint8_t b = (uint8_t)((seed + i * 131) & 0xFF);
    dst[i] = b;
    sum += b;
  }
  return sum;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <port>\n", argv[0]);
    return 2;
  }
  tpr_channel *ch = tpr_channel_create("127.0.0.1", atoi(argv[1]), 10000);
  if (!ch) {
    fprintf(stderr, "connect failed\n");
    return 1;
  }
  tpr_call *c = tpr_call_start(ch, "/lease.S/Check", nullptr, 0, 30000);
  if (!c) {
    fprintf(stderr, "call start failed\n");
    return 1;
  }

  // Odd sizes so the cumulative spans WALK the 4MB ring edge (one of the
  // leases necessarily wraps); interleave a classic copy send to prove
  // the two paths share the stream safely.
  const size_t sizes[] = {700001, 999983, 1048576, 524287, 1000003,
                          999999, 777777, 888888, 1048575};
  std::vector<uint64_t> sums;
  int wrapped = 0;
  for (size_t k = 0; k < sizeof(sizes) / sizeof(sizes[0]); ++k) {
    size_t len = sizes[k];
    if (k == 3) {  // classic staging send in the middle of the lease runs
      std::vector<uint8_t> buf(len);
      sums.push_back(fill_pattern(buf.data(), len, k));
      if (tpr_call_send(c, buf.data(), len, 0) != 0) {
        fprintf(stderr, "classic send failed\n");
        return 1;
      }
      continue;
    }
    uint8_t *p1, *p2;
    size_t l1, l2;
    if (tpr_call_send_reserve(c, len, 0, &p1, &l1, &p2, &l2) != 0) {
      fprintf(stderr, "reserve failed at msg %zu\n", k);
      return 1;
    }
    // misuse guard: a second reserve while holding the lease must fail
    // fast with -1 (NOT deadlock on the held send lock)
    {
      uint8_t *x1, *x2;
      size_t y1, y2;
      if (tpr_call_send_reserve(c, 64, 0, &x1, &y1, &x2, &y2) != -1) {
        fprintf(stderr, "double reserve not rejected\n");
        return 1;
      }
    }
    if (l2) ++wrapped;
    // one continuous pattern across the (possibly split) span — the
    // server sees a single logical message either way; the second
    // segment resumes the stream at byte l1 (seed + l1*131)
    uint64_t sum = fill_pattern(p1, l1, k);
    if (l2) sum += fill_pattern(p2, l2, k + (uint64_t)l1 * 131);
    sums.push_back(sum);
    if (tpr_call_send_commit(c) != 0) {
      fprintf(stderr, "commit failed\n");
      return 1;
    }
  }
  // misuse guard: commit with no lease held is -1
  if (tpr_call_send_commit(c) != -1) {
    fprintf(stderr, "stray commit not rejected\n");
    return 1;
  }
  tpr_call_writes_done(c);

  // server replies one "len:sum" line per message, in order
  for (size_t k = 0; k < sums.size(); ++k) {
    uint8_t *data;
    size_t len;
    if (tpr_call_recv(c, &data, &len) != 1) {
      fprintf(stderr, "missing verdict %zu\n", k);
      return 1;
    }
    std::string line((char *)data, len);
    tpr_buf_free(data);
    char expect[64];
    snprintf(expect, sizeof expect, "%zu:%" PRIu64, sizes[k], sums[k]);
    if (line != expect) {
      fprintf(stderr, "msg %zu mismatch: server %s, client %s\n", k,
              line.c_str(), expect);
      return 1;
    }
  }
  int st = tpr_call_finish(c, nullptr, 0);
  tpr_call_destroy(c);
  tpr_channel_destroy(ch);
  if (st != TPR_OK) {
    fprintf(stderr, "finish status %d\n", st);
    return 1;
  }
  if (wrapped == 0) {
    fprintf(stderr, "no lease wrapped the ring edge (sizes need retuning)\n");
    return 1;
  }
  printf("LEASE-OK wrapped=%d\n", wrapped);
  return 0;
}
