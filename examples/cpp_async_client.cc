// C++ completion-queue async client example — the reference's CQ-based
// async API shape (grpc_completion_queue_next, completion_queue.cc:393;
// examples/cpp/helloworld's async greeter) over tpurpc's native surface.
//
// Build (the test suite does this automatically):
//   g++ -std=c++17 -O2 examples/cpp_async_client.cc \
//       native/src/tpurpc_client.cc native/src/ring.cc \
//       -Inative/include -lpthread -o /tmp/tpurpc_cpp_async
// Run: /tmp/tpurpc_cpp_async <port>
// GRPC_PLATFORM_TYPE=RDMA_* swaps the byte pipe, app code unchanged.
//
// Exercises: N pipelined async unary calls on one channel driven by a
// single cq_next loop (the throughput shape the blocking API cannot
// express), streaming via tagged recv ops, a deadline enforced by the
// cq puller, and queue shutdown/drain.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "tpurpc/client.h"

static intptr_t TAG(int i) { return i; }

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <port>\n", argv[0]);
    return 2;
  }
  tpr_channel *ch = tpr_channel_create("127.0.0.1", atoi(argv[1]), 5000);
  if (!ch) {
    fprintf(stderr, "connect failed\n");
    return 1;
  }
  tpr_cq *cq = tpr_cq_create();
  tpr_event ev;
  bool all_ok = true;

  // -- 1. pipelined async unary: 64 in flight, one completion loop --------
  enum { N = 64 };
  tpr_call *calls[N];
  for (int i = 0; i < N; i++) {
    std::string req = "r" + std::to_string(i);
    calls[i] = tpr_unary_call_cq(
        ch, "/demo.Greeter/Echo",
        reinterpret_cast<const uint8_t *>(req.data()), req.size(), 10000, cq,
        reinterpret_cast<void *>(TAG(i)));
    if (!calls[i]) {
      fprintf(stderr, "start %d failed\n", i);
      return 1;
    }
  }
  int done = 0, matched = 0;
  while (done < N) {
    if (tpr_cq_next(cq, &ev, 10000) != 1) {
      fprintf(stderr, "cq_next stalled at %d\n", done);
      return 1;
    }
    if (ev.type != TPR_EV_FINISH || ev.status != TPR_OK) {
      fprintf(stderr, "bad completion type=%d status=%d\n", ev.type,
              ev.status);
      all_ok = false;
    }
    int i = static_cast<int>(reinterpret_cast<intptr_t>(ev.tag));
    std::string want = "r" + std::to_string(i);
    if (ev.data && ev.len == want.size() &&
        memcmp(ev.data, want.data(), ev.len) == 0)
      matched++;
    if (ev.data) tpr_buf_free(ev.data);
    done++;
  }
  for (int i = 0; i < N; i++) tpr_call_destroy(calls[i]);
  printf("async_unary done=%d matched=%d\n", done, matched);

  // -- 1b. large async unary (fragmenting send path, >1 MiB frame bound) ---
  std::string big(3u << 20, 'B');
  tpr_call *bigcall = tpr_unary_call_cq(
      ch, "/demo.Greeter/Echo", reinterpret_cast<const uint8_t *>(big.data()),
      big.size(), 30000, cq, reinterpret_cast<void *>(TAG(500)));
  bool big_ok = false;
  if (bigcall && tpr_cq_next(cq, &ev, 30000) == 1 &&
      ev.type == TPR_EV_FINISH) {
    if (ev.status == TPR_OK && ev.data && ev.len == big.size() &&
        memcmp(ev.data, big.data(), ev.len) == 0)
      big_ok = true;
    if (ev.data) tpr_buf_free(ev.data);
  }
  if (bigcall) tpr_call_destroy(bigcall);
  printf("big_async_ok=%d\n", big_ok ? 1 : 0);

  // -- 2. streaming via tagged recv ops ------------------------------------
  tpr_call *stream = tpr_call_start_cq(ch, "/demo.Greeter/Chat", nullptr, 0,
                                       10000, cq);
  if (!stream) {
    fprintf(stderr, "stream start failed\n");
    return 1;
  }
  for (int i = 0; i < 3; i++) {
    std::string m = "m" + std::to_string(i);
    tpr_call_send(stream, reinterpret_cast<const uint8_t *>(m.data()),
                  m.size(), 0);
  }
  tpr_call_writes_done(stream);
  tpr_call_finish_cq(stream, reinterpret_cast<void *>(TAG(999)));
  int got = 0, fin_status = -1;
  bool eos = false, finished = false;
  tpr_call_recv_cq(stream, reinterpret_cast<void *>(TAG(100)));
  while (!finished || !eos) {
    if (tpr_cq_next(cq, &ev, 10000) != 1) {
      fprintf(stderr, "stream cq_next stalled\n");
      return 1;
    }
    if (ev.type == TPR_EV_RECV) {
      if (ev.ok) {
        printf("stream=%.*s\n", static_cast<int>(ev.len), ev.data);
        tpr_buf_free(ev.data);
        got++;
        tpr_call_recv_cq(stream, reinterpret_cast<void *>(TAG(100 + got)));
      } else {
        eos = true;
      }
    } else if (ev.type == TPR_EV_FINISH) {
      fin_status = ev.status;
      finished = true;
    }
  }
  tpr_call_destroy(stream);
  printf("stream_status=%d got=%d\n", fin_status, got);

  // -- 3. deadline enforced by the cq puller -------------------------------
  tpr_call *slow = tpr_unary_call_cq(ch, "/demo.Greeter/Hang", nullptr, 0,
                                     300, cq, reinterpret_cast<void *>(TAG(7)));
  int dl_status = -1;
  if (slow && tpr_cq_next(cq, &ev, 10000) == 1 && ev.type == TPR_EV_FINISH) {
    dl_status = ev.status;
    if (ev.data) tpr_buf_free(ev.data);
  }
  if (slow) tpr_call_destroy(slow);
  printf("deadline_status=%d\n", dl_status);

  // -- 4. shutdown drains then reports -------------------------------------
  tpr_cq_shutdown(cq);
  int sd = tpr_cq_next(cq, &ev, 1000);
  printf("shutdown_rc=%d\n", sd);
  tpr_cq_destroy(cq);
  tpr_channel_destroy(ch);

  return (all_ok && done == N && matched == N && big_ok && got == 3 &&
          fin_status == TPR_OK && dl_status == TPR_DEADLINE_EXCEEDED &&
          sd == -1)
             ? 0
             : 1;
}
