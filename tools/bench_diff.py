#!/usr/bin/env python3
"""tpurpc-oracle bench diff (ISSUE 20): compare two ``BENCH_r*.json``
snapshots and flag regressions, with waterfall-hop attribution.

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py old.json new.json --threshold 5 --json

Every numeric series in ``parsed`` is compared direction-aware:
``value`` / ``*_qps`` / ``*_gbps`` / ``*_mfu`` are higher-better;
``*_pct`` / ``*_us`` / ``*_ns`` are lower-better (gate constants
``*_gate_pct`` and booleans are skipped). A move of more than the
threshold (default 10%) in the bad direction on a **gated** series — one
that carries a ``*_gate_pct`` acceptance gate, plus the headline
throughput/serving series — is a REGRESSION and the tool exits 1, so it
slots straight into CI. When the regressed series is a throughput and
both snapshots carry ``waterfall_gbps_by_hop``, the diff names the hop
whose relative drop is worst — the same attribution the live lens
waterfall gives, applied to the delta ("the regression lives in the
scatter hop"), instead of a bare "0.68 → 0.55 GB/s".

Old snapshots whose ``parsed`` is null (a crashed run, e.g. the r01
seed) still diff: every series in the other file reports as
added/removed rather than crashing the tool.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# Headline series that count as gated even without a *_gate_pct twin:
# the numbers the README tracks release over release.
_HEADLINE = frozenset({
    "value", "serving_qps", "device_infer_qps", "serving_mfu",
    "device_mfu",
})

_SKIP_SUFFIXES = ("_gate_pct", "_pass", "_error")
_SKIP_KEYS = frozenset({
    "n", "rc", "metric", "unit", "calibration", "fallback",
    "fallback_reason", "device_kind", "jax_platform", "serving_model",
    "peak_flops", "peak_flops_assumed", "peak_flops_source",
    "model_flops_per_inference", "serving_requests",
    "serving_client_depth", "serving_client_mode", "host_load",
})


def _higher_better(name: str) -> Optional[bool]:
    """True = higher is better, False = lower is better, None = unknown
    (unknown series are reported but never flagged)."""
    if name in ("value",) or name.endswith(("_qps", "_gbps", "_mfu")):
        return True
    if name.endswith(("_pct", "_us", "_ns", "_ms")):
        return False
    return None


def _numeric_series(doc: dict) -> Dict[str, float]:
    parsed = doc.get("parsed") or {}
    out: Dict[str, float] = {}
    for k, v in parsed.items():
        if k in _SKIP_KEYS or k.endswith(_SKIP_SUFFIXES):
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[k] = float(v)
    return out


def _gated_names(doc: dict) -> frozenset:
    parsed = doc.get("parsed") or {}
    gated = {k[:-len("_gate_pct")] + "_pct" for k in parsed
             if k.endswith("_gate_pct")}
    return frozenset(gated | _HEADLINE)


def _hop_attribution(old: dict, new: dict) -> Optional[dict]:
    """Worst relative per-hop drop between the two waterfall snapshots."""
    oh = (old.get("parsed") or {}).get("waterfall_gbps_by_hop") or {}
    nh = (new.get("parsed") or {}).get("waterfall_gbps_by_hop") or {}
    worst: Optional[Tuple[str, float, float, float]] = None
    for hop in oh:
        if hop not in nh:
            continue
        try:
            o, n = float(oh[hop]), float(nh[hop])
        except (TypeError, ValueError):
            continue
        if o <= 0:
            continue
        drop_pct = (o - n) / o * 100.0
        if worst is None or drop_pct > worst[3]:
            worst = (hop, o, n, drop_pct)
    if worst is None:
        return None
    hop, o, n, drop = worst
    return {"hop": hop, "old_gbps": round(o, 3), "new_gbps": round(n, 3),
            "drop_pct": round(drop, 1)}


def diff_docs(old: dict, new: dict, threshold_pct: float = 10.0) -> dict:
    """The machine-readable diff: per-series rows, flagged regressions,
    and (when a throughput regressed) the waterfall hop to blame."""
    a, b = _numeric_series(old), _numeric_series(new)
    gated = _gated_names(old) | _gated_names(new)
    rows: List[dict] = []
    regressions: List[dict] = []
    for name in sorted(set(a) | set(b)):
        if name == "waterfall_gbps_by_hop":
            continue
        if name not in a:
            rows.append({"series": name, "old": None, "new": b[name],
                         "status": "added"})
            continue
        if name not in b:
            rows.append({"series": name, "old": a[name], "new": None,
                         "status": "removed"})
            continue
        o, n = a[name], b[name]
        delta_pct = ((n - o) / abs(o) * 100.0) if o else 0.0
        hb = _higher_better(name)
        if hb is None:
            status = "unscored"
        else:
            bad = -delta_pct if hb else delta_pct
            if bad > threshold_pct and name in gated:
                status = "REGRESSED"
            elif bad > threshold_pct:
                status = "worse"       # >threshold but not a gated series
            elif -bad > threshold_pct:
                status = "improved"
            else:
                status = "ok"
        row = {"series": name, "old": o, "new": n,
               "delta_pct": round(delta_pct, 1),
               "direction": ("higher-better" if hb
                             else "lower-better" if hb is False
                             else "unknown"),
               "status": status, "gated": name in gated}
        rows.append(row)
        if status == "REGRESSED":
            reg = dict(row)
            if hb and (name == "value" or name.endswith(("_qps", "_gbps"))):
                attr = _hop_attribution(old, new)
                if attr:
                    reg["slowest_hop"] = attr
            regressions.append(reg)
    return {"threshold_pct": threshold_pct, "rows": rows,
            "regressions": regressions,
            "ok": not regressions}


def render(doc: dict, old_name: str, new_name: str) -> str:
    out = [f"bench diff: {old_name} -> {new_name} "
           f"(threshold {doc['threshold_pct']:g}%)"]
    width = max((len(r["series"]) for r in doc["rows"]), default=10)
    for r in doc["rows"]:
        if r["status"] in ("added", "removed"):
            out.append(f"  {r['series']:<{width}}  {r['status']}")
            continue
        mark = {"REGRESSED": "!!", "worse": " -", "improved": " +",
                "ok": "  ", "unscored": " ?"}[r["status"]]
        out.append(
            f"{mark}{r['series']:<{width}}  {r['old']:>12.4g} -> "
            f"{r['new']:>12.4g}  {r['delta_pct']:+7.1f}%  {r['status']}")
    for reg in doc["regressions"]:
        line = (f"REGRESSION: {reg['series']} "
                f"{reg['old']:g} -> {reg['new']:g} "
                f"({reg['delta_pct']:+.1f}%, {reg['direction']})")
        hop = reg.get("slowest_hop")
        if hop:
            line += (f" — worst hop: {hop['hop']} "
                     f"{hop['old_gbps']:g} -> {hop['new_gbps']:g} GB/s "
                     f"({hop['drop_pct']:g}% drop)")
        out.append(line)
    if doc["ok"]:
        out.append("no gated regressions")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/bench_diff.py",
        description="diff two BENCH_r*.json snapshots, flag >threshold "
                    "regressions on gated series, attribute to the "
                    "slowest waterfall hop")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable diff")
    args = ap.parse_args(argv)
    try:
        with open(args.old, encoding="utf-8") as f:
            old = json.load(f)
        with open(args.new, encoding="utf-8") as f:
            new = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"bench_diff: {exc}", file=sys.stderr)
        return 2
    doc = diff_docs(old, new, threshold_pct=args.threshold)
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        sys.stdout.write(render(doc, args.old, args.new))
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
