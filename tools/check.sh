#!/usr/bin/env bash
# tpurpc verification gate: lint + model check + (toolchain permitting)
# sanitizer builds of the native plane. Run from the repo root:
#
#   tools/check.sh            # everything available on this host
#   tools/check.sh --fast     # python-side checks only (no native builds)
#
# Exit 0 iff every check that COULD run passed; unavailable toolchain steps
# are reported as SKIP, never as silent success of something that didn't run.
set -u
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

fail=0
note() { printf '== %s\n' "$*"; }

# 1) ruff, when installed (config lives in pyproject.toml [tool.ruff])
if command -v ruff >/dev/null 2>&1; then
    note "ruff"
    ruff check tpurpc/ tests/ || fail=1
else
    note "ruff: SKIP (not installed)"
fi

# 2) the tpurpc-specific static gate: AST lint (+ suppression audit) +
#    bounded exhaustive ring model check + mutant kill check + the
#    protocol-machine self-test + the quick schedule exploration + the
#    quick distributed simulation (see tpurpc/analysis/)
note "python -m tpurpc.analysis (lint + ringcheck + mutants + protocol + schedule + simnet)"
python -m tpurpc.analysis || fail=1

# 2a) tpurpc-proof schedule-quick (ISSUE 12): the CHESS-style explorer
#     over the LIVE classes — every scenario (HandoffRing producers,
#     DecodeScheduler admission, rendezvous peer-death, KV refcounts)
#     exhausted clean at preemption bound 1, every seeded real-code
#     mutant (hoisted publish, removed locks, skipped quarantine) KILLED
#     by exploration. ~10s, no jax.
note "tpurpc-proof schedule-quick (deterministic exploration, live code)"
python -m tpurpc.analysis schedule --quick || fail=1

# 2a2) tpurpc-simnet simnet-quick (ISSUE 17): the deterministic
#      DISTRIBUTED simulation — the real DisaggDecode/_KvShipper/migrate/
#      DecodeScheduler/CtrlPlane classes as simulated nodes, every
#      cross-process frame/write/kick an explorable courier delivery.
#      All six scenarios (handoff, sender-death reap, adopt-vs-drain,
#      park/kick, close-vs-complete, live migration) explored clean and
#      every seeded distributed mutant KILLED by message-level
#      exploration (a violating delivery order or a reported deadlock).
#      ~20s (<=30s budget), no jax.
note "tpurpc-simnet simnet-quick (distributed simulation, live code)"
python -m tpurpc.analysis simnet --quick || fail=1

#     flight dumps from the smokes below land here; the protocol
#     conformance stage at the end replays them against the declared
#     event machines (tpurpc-proof, ISSUE 12)
FLIGHT_DUMPS="$(mktemp -d /tmp/tpurpc-flight-dumps.XXXXXX)"

# 2b) serving-pipeline smoke (ISSUE 3): depth-4 loopback, 32 pipelined
#     requests over pool AND inline dispatch — every future must complete
#     and demux to the stream that asked. Catches pipelining regressions
#     (demux mix-ups, window wedges, coalescing corruption) in ~1s, no jax.
note "serving pipeline smoke (depth=4, 32 reqs)"
python -m tpurpc.tools.serving_smoke || fail=1

# 2c) tpurpc-scope metrics smoke (ISSUE 4): start a server, scrape the
#     SAME serving port over plain HTTP, assert the core series are
#     present and monotonic across two scrapes, and that a forced-sampled
#     call yields a unified span tree + chrome trace export. ~1s, no jax.
#     (The new `log` lint rule runs inside `python -m tpurpc.analysis`
#     above — hot-path log calls must sit behind a TraceFlag guard.)
note "tpurpc-scope metrics smoke (scrape + spans)"
python -m tpurpc.tools.obs_smoke || fail=1

# 2d) tpurpc-blackbox watchdog smoke (ISSUE 5): with TPURPC_TRACE_SAMPLE=0,
#     wedge a ring sender and a handler on purpose — the stall watchdog
#     must diagnose each within two sweep periods naming the right stage
#     (credit-starvation / device-infer), the wedged call's span tree must
#     exist via tail capture, and /debug/flight must replay the ordered
#     event sequence. ~1.5s, no jax.
note "tpurpc-blackbox watchdog smoke (wedge + diagnose + tail capture)"
python -m tpurpc.tools.watchdog_smoke || fail=1

# 2e) tpurpc-fleet smoke (ISSUE 6): 3 servers behind round_robin, hedged
#     clients; one server degrades + dies, another drains mid-traffic —
#     zero failed RPCs, hedge + drain flight events present and ordered.
#     ~3s, no jax.
note "tpurpc-fleet smoke (kill + drain under hedged traffic)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" python -m tpurpc.tools.fleet_smoke || fail=1

# 2f) tpurpc-manycore smoke (ISSUE 7): 2 forked shard workers behind one
#     SO_REUSEPORT port, pipelined depth-4 traffic — both shards must serve
#     calls, and the MERGED /metrics + /debug/flight (fetched through the
#     serving port) must carry per-shard series. ~2s, no jax.
note "tpurpc-manycore smoke (2 shards, accept spread, merged scrape)"
python -m tpurpc.tools.shard_smoke || fail=1

# 2g) tpurpc-express smoke (ISSUE 9): one 8 MiB tensor rendezvous'd over
#     the shm ring plane AND loopback TCP — the copy ledger must show the
#     one-sided write with ZERO host landing copies, the flight ring the
#     ordered offer/claim/write/complete, and an induced claim-starved
#     stall must be attributed to the `rendezvous` watchdog stage (then
#     complete via the framed fallback). ~20s (jax on cpu, 2 subprocesses).
note "tpurpc-express rendezvous smoke (8 MiB, shm + TCP, zero-copy ledger)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" JAX_PLATFORMS=cpu \
    python -m tpurpc.tools.rendezvous_smoke || fail=1

# 2g1b) tpurpc-pulse smoke (ISSUE 13): descriptor-ring control plane —
#      a server SUBPROCESS and the client stream 1 MiB tensors over shm
#      rings with ring adoption asserted via flight, ZERO framed control
#      ops on either side after warmup (every OFFER/CLAIM/COMPLETE rides
#      the ring), and an induced stuck ring (frozen consumers) attributed
#      to the `ctrl-ring` watchdog stage before the framed fallback
#      completes the call. ~15s, jax on cpu.
note "tpurpc-pulse ctrlring smoke (2 processes, zero control frames)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" JAX_PLATFORMS=cpu \
    python -m tpurpc.tools.ctrlring_smoke || fail=1

# 2g1c) tpurpc-ironclad smoke (ISSUE 18): the NATIVE-plane rendezvous —
#      one 8 MiB tensor native<->native with the C ledger showing the
#      one-sided write (rdv_bytes_sent >= payload, < 64 KiB host copy,
#      ZERO framed control ops), a python->native-subprocess transfer
#      with the ORDERED offer/claim/write/complete flight and a clean
#      python copy ledger, and an induced frozen C consumer attributed
#      to the `ctrl-ring` watchdog stage before the framed fallback
#      completes the call. ~15s, no jax.
note "tpurpc-ironclad native rdv smoke (C plane, zero-copy ledger)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" JAX_PLATFORMS=cpu \
    python -m tpurpc.tools.native_rdv_smoke || fail=1

# 2g1d) tpurpc-xray smoke (ISSUE 19): the C observability plane — a
#      native<->native 4 MiB stream whose merged /debug/flight carries
#      the C plane's ORDERED offer/claim/complete next to the python
#      lane on one monotonic clock, the native metrics table scraped
#      (native_rdv_send_bytes >= payload) with the waterfall's native
#      hops live, and an induced frozen C consumer attributed to the
#      `native-ctrl-frozen` watchdog stage from C evidence ALONE before
#      the framed fallback completes the calls. Its merged dump rides
#      the protocol-conformance stage below (the C plane's events replay
#      through the same machines). ~15s, no jax.
note "tpurpc-xray native obs smoke (merged C+py flight, C-evidence stall)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" JAX_PLATFORMS=cpu \
    python -m tpurpc.tools.native_obs_smoke || fail=1

# 2g2) tpurpc-cadence smoke (ISSUE 10): interactive + batch clients
#      stream off one continuous-batching decode server — per-token order
#      + exact reference values, a mid-decode join between step events,
#      one shed (with pushback + healthz "shedding") under an
#      offered-load burst, and an induced slow step attributed to the
#      `decode-step` watchdog stage. ~5s, no jax.
#      ... with the LIVE protocol verifier armed (TPURPC_VERIFY_PROTOCOL):
#      a violated flight machine would emit proto-violation and trip the
#      watchdog, failing the smoke's healthz/flight assertions
note "tpurpc-cadence smoke (continuous batching + shed + decode-step)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" TPURPC_VERIFY_PROTOCOL=1 \
    python -m tpurpc.tools.serving_gen_smoke || fail=1

# 2g3) tpurpc-keystone smoke (ISSUE 11): one prefill + one decode PROCESS
#      over shm block grants — the copy ledger must prove the KV blocks
#      landed in the decode arena with zero host landing copies (control
#      frames only), token values must equal the reference exactly across
#      the process split, and a repeated prompt must score a prefix-cache
#      hit (warm handoff ships exactly one entry). ~10s, no jax.
note "tpurpc-keystone disagg smoke (2 processes, zero-copy KV handoff)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" python -m tpurpc.tools.disagg_smoke \
    || fail=1

# 2g3a) tpurpc-odyssey smoke (ISSUE 15): a disagg pair over shm (prefill
#      child process + two decode servers) serving ONE account's stream,
#      live-migrated mid-decode — tokens exact across all three hops,
#      ONE trace_id's journey doc with >=2 clock-anchored process lanes
#      (seq-ship/seq-decode/seq-migrate spans present), /debug/seq
#      attributing >=95% of device-step time with the account rollup,
#      and the SEQ_* flight journey protocol-conformant. ~5s, no jax.
note "tpurpc-odyssey smoke (journey + /debug/seq across a migration)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" python -m tpurpc.tools.odyssey_smoke \
    || fail=1

# 2g3b) tpurpc-argus smoke (ISSUE 14): one server + one client + a
#      collector PROCESS polling it at 4 Hz, burn-rate windows scaled to
#      fractions of a second — an induced p99 degradation must take the
#      SLO alert pending->firing within two fast windows, /fleet/slo on
#      the collector must show it under the right member label, /healthz
#      must answer the structured slo-firing reason, and exactly one
#      rate-limited evidence bundle must land on disk with its flight
#      dump passing protocol conformance unmodified. ~8s, no jax.
note "tpurpc-argus smoke (slo burn-rate -> fleet collector -> bundle)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" python -m tpurpc.tools.argus_smoke \
    || fail=1

# 2g3b2) tpurpc-oracle diagnose smoke (ISSUE 20): three induced fault
#      classes (open send-lease -> credit-starvation, quiet-transport
#      slow peer -> device-infer, TPURPC_TEST_FREEZE_NCTRL frozen C
#      consumer -> native-ctrl-frozen) — for each, the live
#      /debug/diagnose route must rank the injected cause #1 with cited
#      evidence, the watchdog trip must auto-capture a bundle whose
#      diagnosis.json agrees, and replaying that bundle offline through
#      tpurpc.tools.diagnose must return the identical verdict. ~10s.
note "tpurpc-oracle diagnose smoke (induced faults -> rank-1 live == bundle replay)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" python -m tpurpc.tools.diagnose_smoke \
    || fail=1

# 2g3c) tpurpc-hive scale smoke (ISSUE 16): thousands of parked pairs in
#      one process (fd-budget capped toward the 5000-pair target) — every
#      parked pair must shed its rings to the shared RingPool (accounting
#      balances exactly, <=4KiB resident each), a 64-connection slice must
#      wake under pipelined traffic with payloads intact and pool bytes
#      conserved, gauges/counters/flight must agree with ground truth, and
#      the Poller's idle sweep must park + ownerlessly wake a registered
#      pair end-to-end. ~3s, no jax. Its flight dump (PAIR_PARK/PAIR_UNPARK
#      under the `park` machine) feeds the conformance stage below.
note "tpurpc-hive scale smoke (mass park/unpark, pool conservation)"
TPURPC_FLIGHT_DUMP="$FLIGHT_DUMPS" python -m tpurpc.tools.scale_smoke \
    || fail=1

# 2g4) tpurpc-proof protocol conformance (ISSUE 12): every flight dump
#      the smokes above produced (fleet, rendezvous, cadence, keystone —
#      every process, subprocesses included) must conform to the declared
#      per-entity event machines. Tolerant mode: a dump may begin
#      mid-history; in-dump transition violations still fail.
note "tpurpc-proof protocol conformance over the smokes' flight dumps"
if [ -n "$(ls "$FLIGHT_DUMPS" 2>/dev/null)" ]; then
    python -m tpurpc.analysis protocol --flight "$FLIGHT_DUMPS" || fail=1
else
    note "protocol conformance: SKIP (no dumps produced?)" ; fail=1
fi
rm -rf "$FLIGHT_DUMPS"

# 2h) tpurpc-lens smoke (ISSUE 8): streaming + serving burst, then assert
#     the sampling profiler names >=3 known stages (>=80% attributed), the
#     /debug/waterfall reports every declared hop with nonzero bytes and a
#     slowest hop, and the timeline tool emits a Perfetto-loadable trace
#     with >=2 clock-anchored process lanes. ~15s (jax on cpu).
note "tpurpc-lens smoke (profiler + waterfall + timeline)"
JAX_PLATFORMS=cpu python -m tpurpc.tools.lens_smoke || fail=1

# 3) the analysis subsystem's own tests, plus a lock-order-instrumented run
#    of the concurrency-heavy suites (TPURPC_DEBUG_LOCKS exercises the
#    CheckedLock shim wired into poller/pair/xds/channel/channelz)
if python -c "import pytest" >/dev/null 2>&1; then
    note "pytest tests/test_analysis.py tests/test_schedule.py tests/test_simnet.py tests/test_protocol.py"
    JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py \
        tests/test_schedule.py tests/test_simnet.py \
        tests/test_protocol.py -q \
        -p no:cacheprovider || fail=1
    note "TPURPC_DEBUG_LOCKS=1 pytest (concurrency suites)"
    JAX_PLATFORMS=cpu TPURPC_DEBUG_LOCKS=1 python -m pytest \
        tests/test_pair.py tests/test_rpc.py tests/test_xds.py \
        tests/test_channelz.py -q -m 'not slow' -p no:cacheprovider \
        || fail=1
else
    note "pytest: SKIP (not installed)"
fi

# 4) sanitizer build + native smoke tests. Prefers cmake (the
#    TPURPC_SANITIZE cache/env option in native/CMakeLists.txt); falls back
#    to direct g++ with the same flags — the container images carry g++ but
#    not always cmake.
if [ "$FAST" = "1" ]; then
    note "native sanitizer builds: SKIP (--fast)"
elif command -v cmake >/dev/null 2>&1 && command -v ninja >/dev/null 2>&1; then
    note "TSan build via cmake (TPURPC_SANITIZE=thread)"
    bdir=native/build/sanitize-cmake
    cmake -G Ninja -B "$bdir" -DTPURPC_SANITIZE=thread native >/dev/null \
        && ninja -C "$bdir" >/dev/null \
        && TSAN_OPTIONS="suppressions=$PWD/native/sanitize/tsan.supp halt_on_error=1" \
           "$bdir/ring_smoke" || fail=1
elif command -v g++ >/dev/null 2>&1; then
    note "TSan build via direct g++ (no cmake on this host)"
    mkdir -p native/build/sanitize
    g++ -std=c++17 -O1 -g -fsanitize=thread -fno-omit-frame-pointer \
        -shared -fPIC native/src/*.cc \
        -o native/build/sanitize/libtpurpc-tsan.so -lpthread -lrt \
        || fail=1
    g++ -std=c++17 -O1 -g -fsanitize=thread -fno-omit-frame-pointer \
        native/src/*.cc native/test/ring_smoke.cc \
        -o native/build/sanitize/ring_smoke-tsan -lpthread -lrt \
        && TSAN_OPTIONS="suppressions=$PWD/native/sanitize/tsan.supp halt_on_error=1" \
           native/build/sanitize/ring_smoke-tsan || fail=1
    note "ASan build + smoke"
    g++ -std=c++17 -O1 -g -fsanitize=address -fno-omit-frame-pointer \
        native/src/*.cc native/test/ring_smoke.cc \
        -o native/build/sanitize/ring_smoke-asan -lpthread -lrt \
        && native/build/sanitize/ring_smoke-asan || fail=1
else
    note "native sanitizer builds: SKIP (no cmake/g++)"
fi

if [ "$fail" = "0" ]; then
    note "ALL CHECKS PASSED"
else
    note "CHECKS FAILED"
fi
exit "$fail"
